"""Schema v11 (health-plane events) + v1–v10 back-compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..10}.py.
Here:

- the v11 addition round-trips: ``health`` records one health-plane
  verdict (device_loss/device_restore/straggler/hedge) with its
  device/alive/wall detail (docs/RESILIENCE.md, "Live elasticity");
- the committed v11 fixture is a REAL elastic serve run — a sharded
  scheduler that lost a device mid-run, live-reshared twice
  (shrink then regrow), hedged a straggler chunk, and still completed
  every request;
- **back-compat**: all TEN committed fixtures — PR 2 (v1) through
  PR 14 (v11) — still load, merge, and render in one ``summarize``
  pass (exit 0) with the health line, while a bogus schema still
  exits 2;
- the ``gol_health_*`` metrics appear once health records are observed.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
    8: DATA / "telemetry_v8" / "pr10run.rank0.jsonl",
    9: DATA / "telemetry_v9" / "pr12run.rank0.jsonl",
    11: DATA / "telemetry_v11" / "pr14run.rank0.jsonl",
}


def _v11_stream(directory, run_id="v11"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "serve", "engine": "auto", "slots": 4,
             "chunk": 2, "mesh_devices": 4}
        )
        ev.health_event("device_loss", generation=4, device=1, alive=3)
        ev.health_event(
            "straggler", generation=8, rank=0, wall_s=0.5,
            baseline_s=0.01, alive=3,
        )
        ev.health_event(
            "hedge", generation=8, bucket="32x32/bitpack",
            winner="primary", agree=True, alive=3,
        )
        ev.health_event("device_restore", generation=10, device=1, alive=4)
        ev.reshard_event(
            generation=4,
            src_mesh={"kind": "1d", "rows": 4, "cols": 1},
            dst_mesh={"kind": "1d", "rows": 2, "cols": 1},
            bytes_moved=16,
            live=True,
            bucket="32x32/bitpack",
        )
        return ev.path


def test_v11_health_roundtrip(tmp_path):
    path = _v11_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 11
    assert set(telemetry.SUPPORTED_SCHEMAS) >= set(range(1, 12))
    health = [r for r in recs if r["event"] == "health"]
    assert [r["verdict"] for r in health] == [
        "device_loss", "straggler", "hedge", "device_restore",
    ]
    assert health[0]["device"] == 1 and health[0]["alive"] == 3
    assert health[1]["wall_s"] == 0.5
    assert health[2]["winner"] == "primary" and health[2]["agree"] is True
    live = next(r for r in recs if r["event"] == "reshard")
    assert live["live"] is True and live["bucket"] == "32x32/bitpack"


def test_committed_fixture_schemas():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v11_fixture_is_a_real_elastic_serve_run():
    """The committed stream came from a sharded scheduler that lost a
    device, live-reshared (shrink AND regrow), hedged a straggler, and
    completed every request — no restart, no failure."""
    recs = [json.loads(ln) for ln in FIXTURES[11].open()]
    assert recs[0]["config"]["driver"] == "serve"
    assert recs[0]["config"]["mesh_devices"] == 4
    verdicts = [r["verdict"] for r in recs if r["event"] == "health"]
    assert {"device_loss", "device_restore", "straggler", "hedge"} <= set(
        verdicts
    )
    reshards = [
        r for r in recs if r["event"] == "reshard" and r.get("live")
    ]
    assert len(reshards) >= 2  # the shrink and the regrow
    shapes = [
        (r["src_mesh"]["rows"], r["dst_mesh"]["rows"]) for r in reshards
    ]
    assert (4, 2) in shapes and (2, 4) in shapes
    faults = {r["site"] for r in recs if r["event"] == "fault"}
    assert faults >= {"device.loss", "rank.slowdown"}
    assert not any(r["event"] == "restart" for r in recs)
    completes = [
        r for r in recs
        if r["event"] == "serve" and r["action"] == "complete"
    ]
    assert len(completes) == 2
    audits = [r for r in recs if r["event"] == "guard_audit"]
    assert audits and all(r["ok"] for r in audits)


def test_v1_to_v11_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v11_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "pr10run", "pr12run", "pr14run", "v11",
    ):
        assert run_id in out
    assert "health:" in out
    assert "device_loss" in out and "straggler" in out


def test_health_metrics_render(tmp_path):
    """The gol_health_* gauges appear once health records land."""
    from gol_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    assert "gol_health_" not in reg.render()  # absent until the plane runs
    for ln in open(_v11_stream(tmp_path)):
        reg.observe(json.loads(ln))
    text = reg.render()
    assert "gol_health_alive_devices 4" in text
    assert "gol_health_device_loss_total 1" in text
    assert "gol_health_device_restore_total 1" in text
    assert "gol_health_straggler_total 1" in text
    assert "gol_health_hedge_total 1" in text
    assert "gol_health_live_reshards_total 1" in text


def test_bogus_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": 99, "run_id": "bad",
             "process_index": 0, "process_count": 1, "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
