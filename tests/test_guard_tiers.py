"""Guard coverage across the tiers built since the guard (PR 10).

The single-world 2-D guard is covered by tests/test_guard.py; here the
extensions the unified fault plane drove (docs/RESILIENCE.md "Guard
coverage"):

- **activity** (``--engine activity``): the audit rides the worklist
  path's board output, rollback reconstructs the changed-tile mask
  all-active (the resume rule), and a guarded fault-free run stays
  bit-identical to the dense tiers;
- **batch** (``--batch``): per-world fingerprints from one vmapped
  audit, rollback replays ONLY the corrupted world's bucket, and the
  cross-engine redundancy audit catches per-world in-range flips;
- **pipelined shard mode**: rollback restores the carried state by
  construction (each chunk program re-exchanges its prologue band from
  the board it is given), pinned by flip-inject-recover on 1-D and 2-D
  meshes with every audit scalar agreeing across shards.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from gol_tpu import compat
from gol_tpu.batch import GolBatchRuntime
from gol_tpu.models import patterns
from gol_tpu.models.state import Geometry
from gol_tpu.resilience import faults
from gol_tpu.runtime import GolRuntime, build_mesh
from gol_tpu.utils import guard as guard_mod

jax.config.update("jax_platforms", "cpu")
compat.set_cpu_device_count(8)


@pytest.fixture(autouse=True)
def _clean_plane():
    faults.clear()
    yield
    faults.clear()


def _flip_plan(at, value, **kw):
    return faults.FaultPlan.from_obj(
        [dict(site="board.bitflip", at=at, value=value, row=10, col=20,
              **kw)]
    )


def _clean(size=64, iters=6):
    rt = GolRuntime(geometry=Geometry(size=size, num_ranks=1), engine="dense")
    _, state = rt.run(pattern=4, iterations=iters)
    return np.asarray(state.board)


def _guarded(engine, size=64, iters=6, mesh=None, redundant=False,
             shard_mode="explicit", halo_depth=1):
    rt = GolRuntime(
        geometry=Geometry(size=size, num_ranks=1),
        engine=engine,
        mesh=mesh,
        shard_mode=shard_mode,
        halo_depth=halo_depth,
    )
    _, state, report = guard_mod.run_guarded(
        rt, pattern=4, iterations=iters,
        config=guard_mod.GuardConfig(check_every=2, redundant=redundant),
    )
    return np.asarray(state.board), report


# -- activity tier -----------------------------------------------------------


def test_activity_guarded_faultfree_matches_dense():
    clean = _clean()
    board, report = _guarded("activity")
    assert report.failures == 0 and report.checks == 3
    assert np.array_equal(board, clean)


def test_activity_guard_detects_and_recovers_oob_flip():
    clean = _clean()
    faults.install(_flip_plan(6, 0xA5))
    board, report = _guarded("activity")
    assert report.failures >= 1 and report.restores >= 1
    assert np.array_equal(board, clean)


def test_activity_guard_redundant_catches_inrange_flip():
    clean = _clean()
    faults.install(_flip_plan(6, -1))
    board, report = _guarded("activity", redundant=True)
    assert report.failures >= 1
    assert np.array_equal(board, clean)


def test_activity_guard_mid_run_flip_recovers():
    """A flip at a mid-run audit boundary: the rollback resets the mask
    all-active, and the replayed evolution reconverges exactly."""
    clean = _clean()
    faults.install(_flip_plan(4, 0xA5))
    board, report = _guarded("activity")
    assert report.failures >= 1
    assert np.array_equal(board, clean)


def test_activity_guard_sharded():
    clean = _clean(size=128)
    faults.install(_flip_plan(6, 0xA5))
    board, report = _guarded("activity", size=128, mesh=build_mesh("1d"))
    assert report.failures >= 1
    assert np.array_equal(board, clean)


def test_activity_stats_still_excluded():
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1), engine="activity",
    )
    rt.stats = True
    with pytest.raises(ValueError, match="--stats applies to unguarded"):
        guard_mod.run_guarded(
            rt, pattern=4, iterations=4,
            config=guard_mod.GuardConfig(check_every=2),
        )


# -- batch tier --------------------------------------------------------------


def _worlds(sizes):
    return [patterns.init_global(4, s, 1) for s in sizes]


def _clean_batch(sizes, iters=6):
    brt = GolBatchRuntime(worlds=_worlds(sizes), engine="auto")
    _, boards = brt.run(iters)
    return [np.asarray(b) for b in boards]


def test_batch_guard_faultfree_matches_unguarded():
    sizes = [64, 64, 96]
    clean = _clean_batch(sizes)
    brt = GolBatchRuntime(
        worlds=_worlds(sizes), engine="auto", guard_every=2
    )
    _, boards = brt.run(6)
    assert brt.last_guard.failures == 0
    # one audit per world per chunk
    assert brt.last_guard.checks == 3 * len(sizes)
    assert all(np.array_equal(a, b) for a, b in zip(boards, clean))


def test_batch_guard_rolls_back_only_the_corrupt_worlds_bucket():
    # Two buckets (64² and 96²-padded); the flip lands in world 2 (the
    # second bucket), so only that bucket replays.
    sizes = [64, 64, 96]
    clean = _clean_batch(sizes)
    faults.install(_flip_plan(6, 0xA5, world=2))
    brt = GolBatchRuntime(
        worlds=_worlds(sizes), engine="auto", guard_every=2
    )
    _, boards = brt.run(6)
    rep = brt.last_guard
    assert rep.failures == 1 and rep.restores == 1
    # The failed audit names world 2's generation; worlds 0/1 audited
    # clean every chunk (3 chunks × 2 worlds) plus world 2's replay.
    bad = [a for a in rep.audits if not a.ok]
    assert len(bad) == 1 and bad[0].max_cell == 0xA5
    assert all(np.array_equal(a, b) for a, b in zip(boards, clean))


def test_batch_guard_redundant_catches_per_world_inrange_flip():
    sizes = [64, 64]
    clean = _clean_batch(sizes)
    faults.install(_flip_plan(6, -1, world=1))
    brt = GolBatchRuntime(
        worlds=_worlds(sizes), engine="auto", guard_every=2,
        guard_redundant=True,
    )
    _, boards = brt.run(6)
    assert brt.last_guard.failures >= 1
    bad = [a for a in brt.last_guard.audits if not a.ok]
    assert bad and bad[0].redundant_fingerprint is not None
    assert all(np.array_equal(a, b) for a, b in zip(boards, clean))


def test_batch_guard_budget_exhaustion_names_world_and_bucket():
    faults.install(
        faults.FaultPlan.from_obj(
            [dict(site="board.bitflip", at=2, value=0xA5, row=1, col=1,
                  world=1, count=-1)]
        )
    )
    brt = GolBatchRuntime(
        worlds=_worlds([64, 64]), engine="auto", guard_every=2,
        guard_max_restores=1,
    )
    with pytest.raises(guard_mod.GuardError, match="world 1"):
        brt.run(6)


def test_batch_guard_knob_validation():
    with pytest.raises(ValueError, match="guard_every"):
        GolBatchRuntime(worlds=_worlds([64]), guard_every=-1)
    with pytest.raises(ValueError, match="requires"):
        GolBatchRuntime(worlds=_worlds([64]), guard_redundant=True)
    with pytest.raises(ValueError, match="second engine"):
        # 48 does not pack into 32-bit words: a dense bucket with no
        # bit-packed counterpart must refuse the redundant audit up
        # front, not mid-run.
        GolBatchRuntime(
            worlds=_worlds([48]), engine="dense", guard_every=2,
            guard_redundant=True,
        )


def test_batch_guard_checkpoints_only_audited_states(tmp_path):
    from gol_tpu.utils import checkpoint as ckpt

    sizes = [64, 64]
    clean = _clean_batch(sizes)
    faults.install(_flip_plan(4, 0xA5, world=0))
    brt = GolBatchRuntime(
        worlds=_worlds(sizes), engine="auto", guard_every=2,
        checkpoint_every=2, checkpoint_dir=str(tmp_path / "ck"),
    )
    _, boards = brt.run(6)
    assert brt.last_guard.failures == 1
    assert all(np.array_equal(a, b) for a, b in zip(boards, clean))
    snaps = ckpt.list_snapshots(str(tmp_path / "ck"), kind="batch")
    assert snaps
    for s in snaps:
        ckpt.verify_snapshot(s)
    # The gen-4 snapshot was written AFTER the failed audit's replay:
    # it must hold the clean world, not the corrupted candidate.
    snap4 = [s for s in snaps if "000000000004" in s]
    assert snap4
    loaded = ckpt.load_batch(snap4[0])
    assert int(loaded.boards[0].max()) <= 1


# -- pipelined shard mode ----------------------------------------------------


@pytest.mark.parametrize(
    "mesh_kind,engine,depth",
    [("1d", "bitpack", 2), ("1d", "dense", 4), ("2d", "dense", 2)],
)
def test_pipeline_guard_flip_on_one_shard_recovers(mesh_kind, engine, depth):
    """Injected flip lands on one shard; the audit scalars replicate,
    every shard takes the same rollback, and the final grid is
    byte-identical to the clean run — the carried (block, bands) pair
    is rebuilt from the restored board by the chunk program's prologue
    exchange."""
    clean = _clean(size=128)
    faults.install(_flip_plan(6, 0xA5))
    board, report = _guarded(
        engine, size=128, mesh=build_mesh(mesh_kind),
        shard_mode="pipeline", halo_depth=depth,
    )
    assert report.failures >= 1 and report.restores >= 1
    assert np.array_equal(board, clean)


def test_pipeline_guard_redundant_inrange_2d():
    clean = _clean(size=128)
    faults.install(_flip_plan(6, -1))
    board, report = _guarded(
        "dense", size=128, mesh=build_mesh("2d"),
        shard_mode="pipeline", halo_depth=2, redundant=True,
    )
    assert report.failures >= 1
    assert np.array_equal(board, clean)


def test_pipeline_guard_faultfree_matches_explicit():
    board, report = _guarded(
        "bitpack", size=128, mesh=build_mesh("1d"),
        shard_mode="pipeline", halo_depth=4,
    )
    assert report.failures == 0
    assert np.array_equal(board, _clean(size=128))
