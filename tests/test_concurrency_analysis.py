"""gol_tpu.analysis lockcheck + spmdcheck: the host-plane passes.

Same doctrine as test_analysis.py: a verifier that has never caught a
bug is a verifier that does not work.  Each committed broken fixture
must fail its pass (the teeth), the clean tree must pass with zero
unwaivered findings, and the waiver ledger must round-trip — entries in
use show as INFO, stale entries and malformed files are themselves
errors.  Pure-AST: nothing here imports jax or evolves a board.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from gol_tpu.analysis import hostwalk, lockcheck, spmdcheck
from gol_tpu.analysis.report import ERROR, INFO, AnalysisReport

FIXTURES = lockcheck.FIXTURE_DIR


def _lock_errors(report, check):
    return [
        f
        for c in report.checks
        if c.check == check
        for f in c.findings
        if f.severity == ERROR
    ]


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return str(p)


# -- teeth: every fixture must fail its pass ---------------------------------


def test_fixture_lock_inversion_flagged():
    cell = lockcheck.LockCellConfig(
        name="fixture/inversion",
        modules=[
            (
                "broken_lock_inversion",
                os.path.join(FIXTURES, "broken_lock_inversion.py"),
            )
        ],
        roots=[],
        guarded={},
    )
    rep, _ = lockcheck.analyze_cell(cell, {})
    errs = _lock_errors(rep, "lock-order")
    assert errs, "inversion fixture produced no lock-order error"
    assert any("cycle" in f.message for f in errs)


def test_fixture_unguarded_write_flagged():
    cell = lockcheck.LockCellConfig(
        name="fixture/unguarded",
        modules=[
            (
                "broken_unguarded_write",
                os.path.join(FIXTURES, "broken_unguarded_write.py"),
            )
        ],
        roots=[],
        guarded={"Worker": "Worker._lock"},
    )
    rep, _ = lockcheck.analyze_cell(cell, {})
    errs = _lock_errors(rep, "guarded-fields")
    assert errs, "unguarded fixture produced no guarded-field error"
    assert any("without" in f.message for f in errs)


def test_fixture_rank_gated_collective_flagged():
    path = os.path.join(FIXTURES, "broken_rank_gated_collective.py")
    findings, _ = spmdcheck.analyze_files([("fixture", path)], {})
    errs = [
        f
        for f in findings
        if f.severity == ERROR and f.check == "spmd-divergence"
    ]
    # both shapes must trip: collective inside the rank branch AND
    # collective after a rank-conditional early return
    assert len(errs) >= 2
    assert any("inside a rank-conditional branch" in f.message for f in errs)
    assert any("early return" in f.message for f in errs)


def test_teeth_reports_pass_with_committed_fixtures():
    teeth = lockcheck.run_lock_teeth()
    assert all(c.status == "PASS" for c in teeth.checks), [
        (c.check, c.status) for c in teeth.checks
    ]
    spmd_teeth = spmdcheck.run_spmd_teeth()
    assert spmd_teeth.status == "PASS"


# -- clean tree --------------------------------------------------------------


def test_head_lockcheck_green():
    """The committed tree carries zero unwaivered lock findings."""
    rep = AnalysisReport()
    rep.engines.extend(lockcheck.run_lock_checks())
    assert rep.exit_code == 0, rep.render_text()


def test_head_spmdcheck_green():
    rep = AnalysisReport()
    rep.engines.extend(spmdcheck.run_spmd_checks())
    assert rep.exit_code == 0, rep.render_text()


def test_head_inventory_names_the_serve_locks():
    reports = lockcheck.run_lock_checks()
    serve = next(r for r in reports if r.config_name == "lock/serve")
    inv = [
        f.message
        for c in serve.checks
        if c.check == "inventory"
        for f in c.findings
    ]
    assert any("ServeScheduler._lock" in m for m in inv)
    assert any("MetricsRegistry._lock" in m for m in inv)
    assert any("[http]" in m for m in inv), "http thread root missing"


def test_head_lock_order_edges_are_acyclic_and_scheduler_rooted():
    reports = lockcheck.run_lock_checks()
    serve = next(r for r in reports if r.config_name == "lock/serve")
    edges = [
        f.message
        for c in serve.checks
        if c.check == "lock-order"
        for f in c.findings
        if f.severity == INFO and f.message.startswith("edge ")
    ]
    assert any(
        "ServeScheduler._lock -> MetricsRegistry._lock" in m for m in edges
    ), edges


def test_cli_concurrency_fast_path():
    from gol_tpu.analysis.__main__ import main as verify_main

    assert verify_main(["--concurrency"]) == 0
    assert verify_main(["--concurrency", "--list"]) == 0


# -- waiver ledger -----------------------------------------------------------


def _waiver_file(tmp_path, data):
    p = tmp_path / "waivers.json"
    p.write_text(json.dumps(data))
    return str(p)


def test_waiver_round_trip(tmp_path):
    """A waived guarded-field finding demotes to INFO and reads as
    in-use; removing the pattern would make the same entry stale."""
    cell = lockcheck.LockCellConfig(
        name="fixture/unguarded",
        modules=[
            (
                "broken_unguarded_write",
                os.path.join(FIXTURES, "broken_unguarded_write.py"),
            )
        ],
        roots=[],
        guarded={"Worker": "Worker._lock"},
    )
    plain_rep, _ = lockcheck.analyze_cell(cell, {})
    keys = {
        f.message.split()[0]
        for f in _lock_errors(plain_rep, "guarded-fields")
    }
    assert keys
    waivers = {k: "test: tolerated torn read" for k in keys}
    rep, used = lockcheck.analyze_cell(cell, waivers)
    assert not _lock_errors(rep, "guarded-fields")
    assert used == set(waivers)
    waived = [
        f
        for c in rep.checks
        if c.check == "guarded-fields"
        for f in c.findings
        if f.severity == INFO and f.message.startswith("waived:")
    ]
    assert len(waived) == sum(
        1 for _ in _lock_errors(plain_rep, "guarded-fields")
    )


def test_stale_waiver_is_an_error(tmp_path):
    path = _waiver_file(
        tmp_path,
        {
            "version": 1,
            "lockcheck": [
                {"key": "Ghost.field", "why": "pattern no longer exists"}
            ],
            "spmdcheck": [],
        },
    )
    reports = lockcheck.run_lock_checks(matrix=[], waiver_path=path)
    wcell = next(r for r in reports if r.config_name == "lock/waivers")
    errs = _lock_errors(wcell, "waivers")
    assert errs and "stale waiver" in errs[0].message


def test_unknown_waiver_section_rejected(tmp_path):
    path = _waiver_file(
        tmp_path, {"version": 1, "lockcheck": [], "typocheck": []}
    )
    with pytest.raises(ValueError, match="unknown sections"):
        lockcheck.load_waivers("lockcheck", path)
    # the runner turns the same rejection into a report-level error
    reports = lockcheck.run_lock_checks(matrix=[], waiver_path=path)
    wcell = next(r for r in reports if r.config_name == "lock/waivers")
    assert _lock_errors(wcell, "waivers")


def test_malformed_waiver_entry_rejected(tmp_path):
    for bad in (
        {"key": "A.b"},  # missing why
        {"key": "A.b", "why": "   "},  # blank why
        {"key": "A.b", "why": "ok", "extra": 1},  # unknown field
    ):
        path = _waiver_file(
            tmp_path, {"version": 1, "lockcheck": [bad], "spmdcheck": []}
        )
        with pytest.raises(ValueError, match="waiver entries"):
            lockcheck.load_waivers("lockcheck", path)


def test_committed_waiver_file_loads_and_is_fully_in_use():
    for section in ("lockcheck", "spmdcheck"):
        assert lockcheck.load_waivers(section) is not None
    reports = lockcheck.run_lock_checks() + spmdcheck.run_spmd_checks()
    for rep in reports:
        if not rep.config_name.endswith("/waivers"):
            continue
        for c in rep.checks:
            assert c.status == "PASS", rep.config_name
            for f in c.findings:
                assert f.message.startswith("in use:"), f.message


# -- analyzer semantics on synthetic programs --------------------------------


def test_self_deadlock_on_plain_lock_reacquire(tmp_path):
    path = _write(
        tmp_path,
        "reacquire.py",
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    self.n += 1
        """,
    )
    cell = lockcheck.LockCellConfig(
        name="fixture/reacquire",
        modules=[("reacquire", path)],
        roots=[("main", "Box.outer")],
        guarded={},
    )
    rep, _ = lockcheck.analyze_cell(cell, {})
    errs = _lock_errors(rep, "lock-order")
    assert errs and "re-acquir" in errs[0].message.lower()


def test_rlock_reentrancy_is_clean(tmp_path):
    path = _write(
        tmp_path,
        "reentrant.py",
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.RLock()
                self.n = 0

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    self.n += 1
        """,
    )
    cell = lockcheck.LockCellConfig(
        name="fixture/reentrant",
        modules=[("reentrant", path)],
        roots=[("main", "Box.outer")],
        guarded={},
    )
    rep, _ = lockcheck.analyze_cell(cell, {})
    assert not _lock_errors(rep, "lock-order")


def test_spmd_divergence_after_return_is_suite_scoped(tmp_path):
    """A rank-gated early return nested inside a block whose every path
    returns must not poison code after the enclosing block (the
    write_host_dumps shape); the same return at function level must."""
    path = _write(
        tmp_path,
        "scoped.py",
        """
        import jax
        from gol_tpu.parallel import multihost

        def nested_escape_is_clean(sharding):
            if sharding is None:
                if jax.process_index() == 0:
                    return 1
                return 0
            return multihost.allgather_host_ints(3)

        def toplevel_escape_diverges():
            if jax.process_index() != 0:
                return 0
            return multihost.allgather_host_ints(3)
        """,
    )
    findings, _ = spmdcheck.analyze_files([("scoped", path)], {})
    errs = [f for f in findings if f.severity == ERROR]
    assert len(errs) == 1, [f.message for f in errs]
    assert "toplevel_escape_diverges" in errs[0].message


def test_spmd_uniform_gate_is_clean(tmp_path):
    """process_count() is rank-uniform — branching on it is fine."""
    path = _write(
        tmp_path,
        "uniform.py",
        """
        import jax
        from gol_tpu.parallel import multihost

        def gather_when_multiprocess():
            if jax.process_count() > 1:
                return multihost.allgather_host_ints(3)
            return [3]
        """,
    )
    findings, _ = spmdcheck.analyze_files([("uniform", path)], {})
    assert not [f for f in findings if f.severity == ERROR]


def test_hostwalk_sees_through_lockwatch_wrap(tmp_path):
    """Wrapping a lock for runtime recording must not hide it from the
    static inventory (or every guarded-field check would go blind)."""
    path = _write(
        tmp_path,
        "wrapped.py",
        """
        import threading
        from gol_tpu.analysis import lockwatch

        class Box:
            def __init__(self):
                self._lock = lockwatch.maybe_wrap(
                    "Box._lock", threading.RLock()
                )
        """,
    )
    prog = hostwalk.Program.load([("wrapped", path)])
    assert prog.classes["Box"].attr_kinds.get("_lock") == "rlock"
