"""The declarative fault-injection plane + containment policies.

Covers (docs/RESILIENCE.md "The fault plane" / "Retry and shed"):

- plan parsing/validation (unknown sites and fields are loud),
  inline-vs-path loading, the GOL_FAULT_PLAN env install, and the
  legacy GOL_CKPT_TEST_WRITE_DELAY alias;
- the trace-identity pin: an installed plan leaves every engine's chunk
  program byte-identical (injection is host-side, between programs);
- checkpoint-write containment: transient IO errors retry to a clean
  snapshot, torn tmps never become candidates, persistent disk-full
  sheds telemetry before checkpoints and NEVER kills the run;
- telemetry-writer containment: a failing rank-file write degrades the
  stream (warn once, drop, ``degraded`` stamp) instead of killing the
  run;
- on-disk snapshot rot is refused by the validated resume walk;
- process faults: crash.exit kills a real child at a chunk boundary and
  an auto-resumed relaunch completes byte-identically; rank.stall fires
  and is recorded.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from gol_tpu.models.state import Geometry
from gol_tpu.resilience import degrade, faults
from gol_tpu.runtime import GolRuntime
from gol_tpu.utils import checkpoint as ckpt

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    faults.clear()
    degrade.drain_reports()
    yield
    faults.clear()
    degrade.drain_reports()


def _plan(*entries):
    return faults.FaultPlan.from_obj(list(entries))


def _flip(at, value=-1, **kw):
    return dict(site="board.bitflip", at=at, value=value, row=5, col=7, **kw)


def _clean_board(engine="dense", iters=6):
    rt = GolRuntime(geometry=Geometry(size=64, num_ranks=1), engine=engine)
    _, state = rt.run(pattern=4, iterations=iters)
    return np.asarray(state.board)


# -- plan surface ------------------------------------------------------------


def test_unknown_site_rejected():
    with pytest.raises(faults.FaultPlanError, match="unknown fault site"):
        _plan({"site": "board.melt"})


def test_unknown_field_rejected():
    with pytest.raises(faults.FaultPlanError, match="unknown fault fields"):
        _plan({"site": "rank.stall", "speling": 1})


def test_bad_count_and_delay_rejected():
    with pytest.raises(faults.FaultPlanError, match="count"):
        _plan({"site": "rank.stall", "count": 0})
    with pytest.raises(faults.FaultPlanError, match="delay_s"):
        _plan({"site": "rank.stall", "delay_s": -1})


def test_load_inline_and_path_and_env(tmp_path, monkeypatch):
    inline = '[{"site": "rank.stall", "delay_s": 0.5}]'
    assert faults.FaultPlan.load(inline).faults[0].delay_s == 0.5
    p = tmp_path / "plan.json"
    p.write_text('{"faults": ' + inline + "}")
    assert faults.FaultPlan.load(str(p)).faults[0].site == "rank.stall"
    with pytest.raises(faults.FaultPlanError, match="cannot read"):
        faults.FaultPlan.load(str(tmp_path / "missing.json"))
    monkeypatch.setenv(faults.PLAN_ENV, inline)
    plan = faults.install_from_env()
    assert plan is not None and faults.active() is plan


def test_attempt_gating(monkeypatch):
    """attempts=1 (default) arms only the first supervised attempt, so
    a crash spec cannot re-kill its own recovery relaunch."""
    faults.install(_plan({"site": "rank.stall", "delay_s": 0.0}))
    monkeypatch.setenv("GOL_RESTART_ATTEMPT", "1")
    assert faults.fire("rank.stall") is None
    monkeypatch.setenv("GOL_RESTART_ATTEMPT", "0")
    assert faults.fire("rank.stall") is not None
    faults.install(
        _plan({"site": "rank.stall", "delay_s": 0.0, "attempts": -1})
    )
    monkeypatch.setenv("GOL_RESTART_ATTEMPT", "7")
    assert faults.fire("rank.stall") is not None


def test_count_consumes_and_drain_ledger():
    faults.install(_plan({"site": "rank.stall", "count": 2}))
    assert faults.fire("rank.stall", 3) is not None
    assert faults.fire("rank.stall", 3) is not None
    assert faults.fire("rank.stall", 3) is None
    fired = faults.drain_fired()
    assert len(fired) == 2 and all(
        f["site"] == "rank.stall" for f in fired
    )
    assert faults.drain_fired() == []


# -- trace identity ----------------------------------------------------------


def test_fault_plan_never_changes_the_traced_program():
    """The jaxpr pin of the acceptance criteria: injection happens
    BETWEEN chunk programs, so an armed plan cannot change a trace."""
    from gol_tpu.analysis import walker

    for engine in ("dense", "bitpack"):
        jaxprs = []
        for armed in (False, True):
            faults.clear()
            if armed:
                faults.install(_plan(_flip(4, value=165)))
            rt = GolRuntime(
                geometry=Geometry(size=64, num_ranks=1), engine=engine
            )
            fn, dynamic, static = rt._evolve_fn(4)
            spec = jax.ShapeDtypeStruct((64, 64), np.uint8)
            jaxprs.append(str(walker.trace_jaxpr(fn, spec, *dynamic, *static)))
        assert jaxprs[0] == jaxprs[1], f"engine {engine} trace diverged"


# -- rename-delay site + legacy alias ----------------------------------------


def test_rename_delay_plan_entry_gaps_the_rename(tmp_path):
    faults.install(
        _plan({"site": "checkpoint.rename_delay", "delay_s": 0.25})
    )
    t0 = time.perf_counter()
    ckpt.save(str(tmp_path / "a.gol.npz"), np.zeros((4, 4), np.uint8), 0, 1)
    assert time.perf_counter() - t0 >= 0.25
    # count=1 default: the second save is gap-free.
    t0 = time.perf_counter()
    ckpt.save(str(tmp_path / "b.gol.npz"), np.zeros((4, 4), np.uint8), 0, 1)
    assert time.perf_counter() - t0 < 0.25


def test_legacy_env_alias_still_works(tmp_path, monkeypatch):
    monkeypatch.setenv(faults.RENAME_DELAY_ENV, "0.25")
    t0 = time.perf_counter()
    ckpt.save(str(tmp_path / "a.gol.npz"), np.zeros((4, 4), np.uint8), 0, 1)
    assert time.perf_counter() - t0 >= 0.25


# -- checkpoint-write containment --------------------------------------------


def test_transient_io_error_retries_to_clean_snapshots(tmp_path):
    clean = _clean_board()
    faults.install(
        _plan({"site": "checkpoint.io_error", "at": 2, "count": 2})
    )
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ck"),
        telemetry_dir=str(tmp_path / "tm"),
        run_id="r",
    )
    _, state = rt.run(pattern=4, iterations=6)
    assert np.array_equal(np.asarray(state.board), clean)
    snaps = ckpt.list_snapshots(str(tmp_path / "ck"))
    assert len(snaps) == 3  # every cadence boundary landed
    for s in snaps:
        ckpt.verify_snapshot(s)
    recs = [
        json.loads(ln) for ln in open(tmp_path / "tm" / "r.rank0.jsonl")
    ]
    assert any(
        r["event"] == "fault" and r["site"] == "checkpoint.io_error"
        for r in recs
    )
    assert any(
        r["event"] == "degraded" and r["action"] == "retried"
        for r in recs
    )


def test_torn_tmp_never_becomes_a_candidate(tmp_path):
    clean = _clean_board()
    faults.install(_plan({"site": "checkpoint.torn_tmp", "at": 2}))
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    _, state = rt.run(pattern=4, iterations=6)
    assert np.array_equal(np.asarray(state.board), clean)
    for s in ckpt.list_snapshots(str(tmp_path / "ck")):
        ckpt.verify_snapshot(s)


def test_persistent_disk_full_sheds_but_finishes(tmp_path, capsys):
    clean = _clean_board()
    faults.install(
        _plan({"site": "checkpoint.disk_full", "at": 2, "count": -1})
    )
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ck"),
        telemetry_dir=str(tmp_path / "tm"),
        run_id="r",
    )
    _, state = rt.run(pattern=4, iterations=6)
    # The run completed with the right grid despite a disk that never
    # accepted one snapshot.
    assert np.array_equal(np.asarray(state.board), clean)
    assert rt._ckpt_shed
    assert ckpt.list_snapshots(str(tmp_path / "ck")) == []
    # The shed order is telemetry first: the stream stamps its own
    # degradation, drops the remaining chunks, and (v13) closes with
    # the census of exactly what the shed cost.
    recs = [
        json.loads(ln) for ln in open(tmp_path / "tm" / "r.rank0.jsonl")
    ]
    assert any(
        r["event"] == "degraded"
        and r["resource"] == "telemetry"
        and r["action"] == "shed"
        for r in recs
    )
    assert recs[-1]["event"] == "degraded"
    assert recs[-1]["action"] == "shed_summary"
    assert recs[-1]["dropped_total"] == sum(recs[-1]["dropped"].values()) > 0
    assert "continuing WITHOUT further checkpoints" in (
        capsys.readouterr().err
    )


def test_genuinely_broken_storage_still_raises(tmp_path):
    """Non-ENOSPC failures past the retry budget surface as before —
    containment is for faults, not for an unwritable directory."""
    faults.install(
        _plan({"site": "checkpoint.io_error", "at": 2, "count": -1})
    )
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    with pytest.raises(OSError, match="injected transient"):
        rt.run(pattern=4, iterations=6)


# -- on-disk rot -------------------------------------------------------------


def test_snapshot_rot_is_refused_by_the_resume_walk(tmp_path):
    faults.install(_plan({"site": "snapshot.bitflip", "at": 6}))
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    rt.run(pattern=4, iterations=6)
    faults.clear()
    newest, skipped = ckpt.latest_valid(str(tmp_path / "ck"))
    assert skipped and "000000000006" in skipped[0]
    assert newest is not None and "000000000004" in newest
    with pytest.raises(ckpt.CorruptSnapshotError):
        ckpt.verify_snapshot(skipped[0])


# -- telemetry-writer containment (satellite) --------------------------------


def test_telemetry_write_failure_degrades_not_kills(tmp_path, capsys):
    clean = _clean_board()
    # ``at`` counts records for this site: let a few land, fail the next.
    faults.install(_plan({"site": "telemetry.write_error", "at": 3}))
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        telemetry_dir=str(tmp_path),
        run_id="r",
    )
    _, state = rt.run(pattern=4, iterations=6)
    assert np.array_equal(np.asarray(state.board), clean)
    err = capsys.readouterr().err
    assert err.count("telemetry degraded") == 1  # warned exactly once
    recs = [json.loads(ln) for ln in open(tmp_path / "r.rank0.jsonl")]
    # The stream holds everything before the failure, then the stamp.
    assert recs[0]["event"] == "run_header"
    assert recs[-1]["event"] == "degraded"
    assert recs[-1]["resource"] == "telemetry"
    assert recs[-1]["action"] == "dropped"
    assert all(r["event"] != "summary" for r in recs)  # shed, not written


def test_real_write_failure_in_stream_is_contained(tmp_path, capsys):
    """The containment is not injection-specific: a file handle that
    dies under the stream degrades instead of raising."""
    from gol_tpu.telemetry import EventLog

    ev = EventLog(str(tmp_path), run_id="x", process_index=0)
    ev.run_header({"driver": "test"})
    ev._f.close()  # the "disk" breaks mid-run
    ev.compile_event(1, 0.0, 0.0)  # must not raise
    ev.compile_event(2, 0.0, 0.0)  # further events silently dropped
    assert ev.degraded is not None
    assert ev.degraded["action"] == "dropped"
    assert capsys.readouterr().err.count("telemetry degraded") == 1
    recs = [json.loads(ln) for ln in open(tmp_path / "x.rank0.jsonl")]
    assert [r["event"] for r in recs] == ["run_header"]
    ev.close()


# -- process faults ----------------------------------------------------------


def test_rank_stall_fires_and_is_recorded(tmp_path):
    faults.install(
        _plan({"site": "rank.stall", "at": 2, "delay_s": 0.05})
    )
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ck"),
        telemetry_dir=str(tmp_path / "tm"),
        run_id="r",
    )
    rt.run(pattern=4, iterations=6)
    recs = [
        json.loads(ln) for ln in open(tmp_path / "tm" / "r.rank0.jsonl")
    ]
    assert any(
        r["event"] == "fault" and r["site"] == "rank.stall" for r in recs
    )


def test_crash_exit_then_auto_resume_completes(tmp_path):
    """A real child process dies at a chunk boundary (os._exit — no
    flush, no atexit) and an auto-resumed relaunch finishes
    byte-identically: the supervisor-child crash site end to end."""
    ref = tmp_path / "ref"
    out = tmp_path / "out"
    ck = str(tmp_path / "ck")
    ref.mkdir()
    out.mkdir()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    world = ["4", "64", "8", "512", "1"]
    subprocess.run(
        [sys.executable, "-m", "gol_tpu", *world, "--outdir", str(ref)],
        env=env, cwd=REPO, check=True,
    )
    plan = json.dumps(
        {"faults": [{"site": "crash.exit", "at": 4, "value": 17}]}
    )
    crashed = subprocess.run(
        [sys.executable, "-m", "gol_tpu", *world, "--outdir", str(out),
         "--checkpoint-every", "2", "--checkpoint-dir", ck,
         "--auto-resume", "--fault-plan", plan],
        env=env, cwd=REPO,
    )
    assert crashed.returncode == 17
    # The relaunch (same argv, attempt 1 — the crash spec is disarmed
    # by its attempts gate) completes the remaining generations.
    env2 = dict(env, GOL_RESTART_ATTEMPT="1")
    subprocess.run(
        [sys.executable, "-m", "gol_tpu", *world, "--outdir", str(out),
         "--checkpoint-every", "2", "--checkpoint-dir", ck,
         "--auto-resume", "--fault-plan", plan],
        env=env2, cwd=REPO, check=True,
    )
    a = (ref / "Rank_0_of_1.txt").read_bytes()
    b = (out / "Rank_0_of_1.txt").read_bytes()
    assert a == b


# -- archive-error hardening (found by the chaos matrix) ---------------------


def test_header_corruption_reads_as_corrupt_snapshot(tmp_path):
    """A flipped byte inside a .npy member header makes numpy's header
    parser raise SyntaxError/TokenError — those must read as 'corrupt
    snapshot', never a traceback (the chaos matrix found this live)."""
    path = str(tmp_path / "a.gol.npz")
    ckpt.save(path, np.zeros((16, 16), np.uint8), 0, 1)
    size = os.path.getsize(path)
    hits = 0
    for offset in range(40, min(size - 1, 200), 7):
        data = bytearray(open(path, "rb").read())
        data[offset] ^= 0xFF
        broken = str(tmp_path / f"b{offset}.gol.npz")
        open(broken, "wb").write(bytes(data))
        try:
            ckpt.load(broken)
        except ckpt.CorruptSnapshotError:
            hits += 1
        # a lucky flip may still load (e.g. in zip padding) — fine;
        # what must NEVER happen is any other exception type.
    assert hits > 0
