"""Pallas fused kernel vs. the oracle — interpret mode on the CPU backend.

The same kernel code compiles through Mosaic on a real TPU (exercised by
bench.py / the driver); interpret mode checks semantics: DMA halo layout,
aligned offsets, lane-roll column wrap, rule fusion.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.ops import pallas_step, stencil

from tests import oracle


random_board = oracle.random_board


@pytest.mark.parametrize(
    "shape,tile",
    [((64, 128), 32), ((128, 128), 32), ((96, 256), 32), ((160, 128), 32)],
)
@pytest.mark.parametrize("seed", [0, 1])
def test_step_matches_oracle(shape, tile, seed):
    board = random_board(*shape, seed)
    got = np.asarray(pallas_step.step_pallas(jnp.asarray(board), tile))
    np.testing.assert_array_equal(got, oracle.step_torus(board))


def test_single_tile_grid():
    """tile == height: the halo blocks wrap to the board's own edges."""
    board = random_board(32, 128, 3)
    got = np.asarray(pallas_step.step_pallas(jnp.asarray(board), 32))
    np.testing.assert_array_equal(got, oracle.step_torus(board))


def test_evolve_matches_dense_engine():
    board = random_board(64, 128, 5)
    got = np.asarray(pallas_step.evolve(jnp.asarray(board), 6, 512))
    want = np.asarray(stencil.run(jnp.asarray(board), 6))
    np.testing.assert_array_equal(got, want)


def test_blinker_period_two():
    board = np.zeros((32, 128), np.uint8)
    board[0, 0] = board[0, 1] = board[0, 127] = 1  # pattern 4's wrap blinker
    one = np.asarray(pallas_step.step_pallas(jnp.asarray(board), 32))
    two = np.asarray(pallas_step.step_pallas(jnp.asarray(one), 32))
    np.testing.assert_array_equal(two, board)
    assert not np.array_equal(one, board)


def test_pick_tile_divides_and_aligns():
    assert pallas_step.pick_tile(16384, 16384, 512) % 32 == 0
    assert 16384 % pallas_step.pick_tile(16384, 16384, 512) == 0
    assert pallas_step.pick_tile(64, 128, 1 << 30) == 64  # capped by height
    # tiny hint clamps up to the minimum aligned tile
    assert pallas_step.pick_tile(64, 128, 1) == 32


def test_pick_tile_vmem_budget_shrinks_with_width():
    wide = pallas_step.pick_tile(16384, 65536, 512)
    narrow = pallas_step.pick_tile(16384, 2048, 512)
    assert wide < narrow
    assert (2 * wide + 2) * 65536 <= 32 * 1024 * 1024  # sane VMEM footprint


def test_rejects_bad_geometry():
    with pytest.raises(ValueError, match="divisible"):
        pallas_step.pick_tile(12, 128, 512)
    with pytest.raises(ValueError, match="multiple"):
        pallas_step.step_pallas(jnp.zeros((32, 128), jnp.uint8), 12)


def test_long_evolution_matches_oracle():
    board = random_board(96, 128, 9)
    got = np.asarray(pallas_step.evolve(jnp.asarray(board), 12, 32))
    np.testing.assert_array_equal(got, oracle.run_torus(board, 12))
