"""Runtime + CLI with a device mesh: sharded end-to-end runs on 8 CPU devices."""

import numpy as np
import pytest

from gol_tpu.models import patterns
from gol_tpu.models.state import Geometry
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.runtime import GolRuntime, build_mesh
from gol_tpu import cli
from gol_tpu.utils import io as gol_io

import jax as _jax

from tests import oracle


def test_runtime_sharded_matches_oracle():
    geom = Geometry(size=8, num_ranks=4)  # 32×8 world
    rt = GolRuntime(geometry=geom, mesh=mesh_mod.make_mesh_1d(4))
    _, state = rt.run(pattern=1, iterations=5)
    board0 = patterns.init_global(1, 8, 4)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 5)
    )


def test_runtime_sharded_2d_matches_oracle():
    geom = Geometry(size=16, num_ranks=2)  # 32×16 world on a 2×4 mesh
    rt = GolRuntime(geometry=geom, mesh=mesh_mod.make_mesh_2d((2, 4)))
    _, state = rt.run(pattern=4, iterations=6)
    board0 = patterns.init_global(4, 16, 2)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 6)
    )


def test_runtime_sharded_bitpack_matches_oracle():
    geom = Geometry(size=32, num_ranks=1)  # 32×32 world, 1-D ring
    rt = GolRuntime(
        geometry=geom, engine="bitpack", mesh=mesh_mod.make_mesh_1d(4)
    )
    _, state = rt.run(pattern=4, iterations=5)
    board0 = patterns.init_global(4, 32, 1)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 5)
    )


def test_runtime_sharded_bitpack_2d_matches_oracle():
    geom = Geometry(size=256, num_ranks=1)  # 256×256 on a 2×4 mesh
    rt = GolRuntime(
        geometry=geom, engine="bitpack", mesh=mesh_mod.make_mesh_2d((2, 4))
    )
    _, state = rt.run(pattern=2, iterations=3)
    board0 = patterns.init_global(2, 256, 1)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 3)
    )


def test_runtime_deep_halo_matches_oracle():
    geom = Geometry(size=8, num_ranks=4)  # 32×8 world
    rt = GolRuntime(
        geometry=geom, mesh=mesh_mod.make_mesh_1d(4), halo_depth=3
    )
    _, state = rt.run(pattern=1, iterations=7)
    board0 = patterns.init_global(1, 8, 4)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 7)
    )


def test_runtime_deep_halo_bitpack_matches_oracle():
    """Packed temporal blocking: k-deep word halos, 1-D and 2-D meshes."""
    geom = Geometry(size=32, num_ranks=4)  # 128×32 world, nw=1 word/shard
    rt = GolRuntime(
        geometry=geom,
        engine="bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
        halo_depth=3,
    )
    _, state = rt.run(pattern=1, iterations=7)
    board0 = patterns.init_global(1, 32, 4)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 7)
    )

    geom2 = Geometry(size=128, num_ranks=1)  # 128×128 over 2×2 blocks
    rt2 = GolRuntime(
        geometry=geom2,
        engine="bitpack",
        mesh=mesh_mod.make_mesh_2d((2, 2), devices=_jax.devices()[:4]),
        halo_depth=2,  # <= 2 words of shard width
    )
    _, state2 = rt2.run(pattern=1, iterations=5)
    board0 = patterns.init_global(1, 128, 1)
    np.testing.assert_array_equal(
        np.asarray(state2.board), oracle.run_torus(board0, 5)
    )


def test_runtime_deep_halo_rejections():
    geom = Geometry(size=16, num_ranks=1)
    with pytest.raises(ValueError, match="sharded runs"):
        GolRuntime(geometry=geom, halo_depth=2)
    # The packed engine's horizontal halo quantum is the 32-cell word: a
    # 2-D shard one word wide cannot supply a 2-word ghost band.
    with pytest.raises(ValueError, match="shard extent"):
        GolRuntime(
            geometry=Geometry(size=64, num_ranks=1),
            engine="bitpack",
            mesh=mesh_mod.make_mesh_2d((2, 2), devices=_jax.devices()[:4]),
            halo_depth=2,
        )
    with pytest.raises(ValueError, match="shard extent"):
        GolRuntime(
            geometry=geom,
            mesh=mesh_mod.make_mesh_1d(8),  # shard h = 2
            halo_depth=3,
        )
    # A size-1 cols axis still halo-extends the width axis: the depth limit
    # must apply to shard width too, eagerly, not at trace time.
    import jax

    with pytest.raises(ValueError, match="shard extent"):
        GolRuntime(
            geometry=Geometry(size=4, num_ranks=4),  # 16×4 world
            mesh=mesh_mod.make_mesh_2d((1, 1), devices=jax.devices()[:1]),
            halo_depth=8,  # > shard width 4, <= shard height 16
        )


def test_runtime_bitpack_mesh_rejects_auto_shard_mode():
    with pytest.raises(ValueError, match="auto-SPMD"):
        GolRuntime(
            geometry=Geometry(size=32, num_ranks=1),
            engine="bitpack",
            shard_mode="auto",
            mesh=mesh_mod.make_mesh_1d(4),
        )


def test_runtime_bitpack_mesh_rejects_unpackable_width():
    with pytest.raises(ValueError, match="shard width"):
        GolRuntime(
            geometry=Geometry(size=16, num_ranks=1),
            engine="bitpack",
            mesh=mesh_mod.make_mesh_2d((2, 4)),  # shard width 4 < 32
        )


def test_runtime_mesh_rejects_pallas_engine():
    with pytest.raises(ValueError, match="sharded path"):
        GolRuntime(
            geometry=Geometry(size=32, num_ranks=1),
            engine="pallas",
            mesh=mesh_mod.make_mesh_1d(4),
        )


def test_runtime_mesh_rejects_stale_halo():
    with pytest.raises(ValueError, match="single-device"):
        GolRuntime(
            geometry=Geometry(size=8, num_ranks=2),
            halo_mode="stale_t0",
            mesh=mesh_mod.make_mesh_1d(2),
        )


def test_runtime_mesh_rejects_indivisible_geometry():
    with pytest.raises(ValueError, match="divisible"):
        GolRuntime(
            geometry=Geometry(size=9, num_ranks=1),
            mesh=mesh_mod.make_mesh_2d((2, 4)),
        )


def test_build_mesh_kinds():
    assert build_mesh("none") is None
    assert dict(build_mesh("1d").shape) == {"rows": 8}
    assert dict(build_mesh("2d").shape) == {"rows": 2, "cols": 4}


def test_cli_mesh_run_writes_correct_dump(capsys, tmp_path):
    """End-to-end: CLI with --mesh 1d on 8 CPU devices; dump must equal the
    single-device (fresh-halo torus) evolution."""
    rc = cli.main(
        ["4", "8", "4", "32", "1"]
        + ["--outdir", str(tmp_path), "--ranks", "8", "--mesh", "1d"]
    )
    assert rc == 0
    board0 = patterns.init_global(4, 8, 8)
    expected = oracle.run_torus(board0, 4)
    for r in range(8):
        _, block = gol_io.read_rank_file(str(tmp_path / f"Rank_{r}_of_8.txt"))
        np.testing.assert_array_equal(block, expected[r * 8 : (r + 1) * 8])


def test_auto_engine_resolution():
    """'auto' is a performance choice; all engines are bit-exact, so it
    should pick the packed paths whenever the geometry allows."""
    # Single device, width packs into words -> bitpack (CPU backend; on TPU
    # the same geometry with lane-filling width resolves to pallas_bitpack).
    rt = GolRuntime(geometry=Geometry(size=64, num_ranks=1))
    assert rt._resolved == "bitpack"
    # Width that doesn't pack -> dense.
    rt = GolRuntime(geometry=Geometry(size=20, num_ranks=1))
    assert rt._resolved == "dense"
    # Reference-compat stale halos are a dense-only path.
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1), halo_mode="stale_t0"
    )
    assert rt._resolved == "dense"
    # Sharded explicit + packable -> packed ring engine.
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=4), mesh=mesh_mod.make_mesh_1d(4)
    )
    assert rt._resolved == "bitpack"
    # Sharded but the shard width doesn't pack -> dense.
    rt = GolRuntime(
        geometry=Geometry(size=16, num_ranks=4), mesh=mesh_mod.make_mesh_1d(4)
    )
    assert rt._resolved == "dense"
    # Overlap on a packable 1-D ring now has a packed program; auto-SPMD
    # remains a dense-only program.
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=4),
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="overlap",
    )
    assert rt._resolved == "bitpack"
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=4),
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="auto",
    )
    assert rt._resolved == "dense"


def test_auto_engine_runs_match_oracle():
    geom = Geometry(size=32, num_ranks=2)
    rt = GolRuntime(geometry=geom)  # auto -> bitpack on CPU
    _, state = rt.run(pattern=4, iterations=5)
    board0 = patterns.init_global(4, 32, 2)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 5)
    )


def test_auto_falls_back_to_dense_for_deep_narrow_halos():
    """auto must not pick bitpack when the requested halo_depth exceeds the
    shard's width in packed words (dense cell-quantum halos still work)."""
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),  # 32x32 shards, 1 word wide
        mesh=mesh_mod.make_mesh_2d((2, 2), devices=_jax.devices()[:4]),
        halo_depth=4,
    )
    assert rt._resolved == "dense"
    _, state = rt.run(pattern=1, iterations=5)
    board0 = patterns.init_global(1, 64, 1)
    np.testing.assert_array_equal(
        np.asarray(state.board), oracle.run_torus(board0, 5)
    )
