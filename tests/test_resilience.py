"""Process-tier resilience tests (gol_tpu/resilience/, docs/RESILIENCE.md).

What they pin:

- **validated discovery**: ``latest_valid`` skips corrupt single-file
  snapshots, torn sharded directories, and writer ``.tmp`` leftovers,
  and reports what it skipped (the fallback signal);
- **cooperative preemption**: a requested preemption (flag or a real
  SIGTERM) stops ``run``/``run_guarded``/the 3-D driver at the next
  chunk boundary with a final fingerprinted checkpoint, a ``preempt``
  telemetry event, and exit code 75 — and the resumed run completes the
  total-iteration target bit-exactly;
- **retention GC**: keep-last-K valid, never the resume source, corrupt
  files left as evidence, ``.tmp`` swept;
- **supervisor**: restarts on crash/preemption, bounded budget, manifest
  records attempts/exit codes/resume generations;
- **no-op**: with resilience knobs set but nothing delivered, traced
  programs are byte-identical (extends the PR 2/3 trace-identity pin);
- **async-writer satellites**: a writer failure on the *final* snapshot
  still surfaces at end of run, and a ``.tmp`` file left by a killed
  writer is never picked up by ``latest``/``latest_valid``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from gol_tpu import resilience
from gol_tpu.models.state import Geometry
from gol_tpu.runtime import GolRuntime
from gol_tpu.utils import checkpoint as ckpt

from tests import oracle

jax.config.update("jax_platforms", "cpu")


def _corrupt_byte(path, offset_frac=0.5):
    with open(path, "r+b") as f:
        f.seek(int(os.path.getsize(path) * offset_frac))
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))


def _make_ckpts(tmp_path, gens=(4, 8, 12), size=16):
    board = oracle.random_board(size, size, seed=1)
    paths = []
    for g in gens:
        p = ckpt.checkpoint_path(str(tmp_path), g)
        ckpt.save(p, board, g, 1)
        paths.append(p)
    return paths


# -- validated discovery -----------------------------------------------------


def test_latest_valid_skips_corrupt_newest(tmp_path):
    p4, p8, p12 = _make_ckpts(tmp_path)
    _corrupt_byte(p12)
    # latest() still prefers the corrupt file (satellite: the raw listing
    # can't know); latest_valid is the one that must not.
    assert ckpt.latest(str(tmp_path)) == p12
    path, skipped = ckpt.latest_valid(str(tmp_path))
    assert path == p8
    assert skipped == [p12]


def test_latest_valid_walks_past_multiple_bad(tmp_path):
    p4, p8, p12 = _make_ckpts(tmp_path)
    board = oracle.random_board(16, 16, seed=1)
    # Deterministic corruption: a stored fingerprint that can't match.
    ckpt.save(p12, board, 12, 1, fingerprint=0xDEADBEEF)
    ckpt.save(p8, board, 8, 1, fingerprint=0xDEADBEEF)
    path, skipped = ckpt.latest_valid(str(tmp_path))
    assert path == p4 and skipped == [p12, p8]
    ckpt.save(p4, board, 4, 1, fingerprint=0xDEADBEEF)
    path, skipped = ckpt.latest_valid(str(tmp_path))
    assert path is None and len(skipped) == 3


def test_latest_valid_ignores_tmp_files(tmp_path):
    (p4,) = _make_ckpts(tmp_path, gens=(4,))
    # A killed writer leaves ckpt_<g>.gol.npz.tmp.npz — never a candidate.
    tmp = ckpt.checkpoint_path(str(tmp_path), 8) + ".tmp.npz"
    with open(tmp, "wb") as f:
        f.write(b"torn half-written garbage")
    assert ckpt.latest(str(tmp_path)) == p4
    path, skipped = ckpt.latest_valid(str(tmp_path))
    assert path == p4 and skipped == []
    assert tmp not in ckpt.list_snapshots(str(tmp_path))


def test_latest_valid_skips_torn_and_corrupt_sharded(tmp_path):
    from tests.test_checkpoint import _sharded_board

    _, arr, _ = _sharded_board(seed=11)
    good = ckpt.sharded_checkpoint_path(str(tmp_path), 10)
    ckpt.save_sharded(good, arr, 10, 1)
    # Torn: manifest missing.
    os.makedirs(ckpt.sharded_checkpoint_path(str(tmp_path), 20))
    # Corrupt: complete dir, one piece byte-flipped (fps stay stored).
    bad = ckpt.sharded_checkpoint_path(str(tmp_path), 30)
    ckpt.save_sharded(bad, arr, 30, 1)
    shards = os.path.join(bad, "shards_00000.npz")
    with np.load(shards) as data:
        arrays = {k: data[k].copy() for k in data.files}
    arrays["piece_0"][0, 0] ^= 1  # in-range flip; piece fp must catch it
    np.savez_compressed(shards, **arrays)
    path, skipped = ckpt.latest_valid(str(tmp_path))
    assert path == good
    assert skipped == [bad, os.path.join(str(tmp_path), "ckpt_000000000020.gol.d")]


def test_verify_snapshot_only_process_checks_own_pieces(tmp_path):
    """only_process=0 must pass a dir whose *other* process's piece is
    bad — each rank vouches only for its own writes; the min-generation
    agreement handles the rest."""
    from tests.test_checkpoint import _sharded_board

    _, arr, _ = _sharded_board(seed=12)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 5)
    ckpt.save_sharded(d, arr, 5, 1)
    # Forge a second process's shard file, then corrupt it: rewrite the
    # manifest so one rect belongs to proc 1 with its own shards file.
    shards0 = os.path.join(d, "shards_00000.npz")
    with np.load(shards0) as data:
        arrays = {k: data[k].copy() for k in data.files}
    n = len(arrays["rects"])
    keep, give = list(range(n - 1)), n - 1
    moved = dict(
        rects=arrays["rects"][[give]].copy(),
        fps=arrays["fps"][[give]].copy(),
        piece_0=arrays[f"piece_{give}"].copy(),
    )
    moved["piece_0"][0, 0] ^= 1  # corrupt proc 1's piece
    np.savez_compressed(os.path.join(d, "shards_00001.npz"), **moved)
    kept = dict(
        rects=arrays["rects"][keep].copy(), fps=arrays["fps"][keep].copy()
    )
    for i, k in enumerate(keep):
        kept[f"piece_{i}"] = arrays[f"piece_{k}"]
    np.savez_compressed(shards0, **kept)
    mpath = os.path.join(d, "manifest.npz")
    with np.load(mpath) as data:
        man = {k: data[k].copy() for k in data.files}
    procs = man["procs"].copy()
    hit = np.nonzero(np.all(man["rects"] == moved["rects"][0], axis=1))[0]
    procs[hit] = 1
    man["procs"] = procs
    np.savez_compressed(mpath, **man)

    assert ckpt.verify_snapshot(d, only_process=0) == 5
    with pytest.raises(ckpt.CorruptSnapshotError):
        ckpt.verify_snapshot(d, only_process=1)
    with pytest.raises(ckpt.CorruptSnapshotError):
        ckpt.verify_snapshot(d)  # full check sees every piece


# -- cooperative preemption --------------------------------------------------


def _final_board(iterations=12, size=32):
    rt = GolRuntime(geometry=Geometry(size=size, num_ranks=1))
    _, st = rt.run(pattern=4, iterations=iterations)
    return np.asarray(st.board)


def test_run_preempts_at_chunk_boundary_with_checkpoint(tmp_path):
    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path),
    )
    resilience.request_preemption()
    try:
        with pytest.raises(resilience.Preempted) as ei:
            rt.run(pattern=4, iterations=12)
    finally:
        resilience.clear_preemption()
    assert ei.value.generation == 2
    assert ei.value.checkpoint_dir == str(tmp_path)
    # The boundary snapshot is durable (writer was flushed pre-raise).
    snap = ckpt.load(ckpt.latest(str(tmp_path)))
    assert snap.generation == 2


def test_preempt_resume_completes_bit_exactly(tmp_path):
    want = _final_board()
    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path),
    )
    resilience.request_preemption()
    try:
        with pytest.raises(resilience.Preempted):
            rt.run(pattern=4, iterations=12)
    finally:
        resilience.clear_preemption()
    path, info = resilience.resolve_auto_resume(str(tmp_path))
    assert path is not None and not info["fallback"]
    rt2 = GolRuntime(geometry=Geometry(size=32, num_ranks=1))
    _, st = rt2.run(
        pattern=4, iterations=12 - info["generation"], resume=path
    )
    np.testing.assert_array_equal(np.asarray(st.board), want)


def test_preempt_without_checkpoint_dir_reports_uncheckpointed():
    rt = GolRuntime(geometry=Geometry(size=32, num_ranks=1))
    # Force a multi-chunk schedule without checkpoints: use guard chunks.
    from gol_tpu.utils.guard import GuardConfig, run_guarded

    resilience.request_preemption()
    try:
        with pytest.raises(resilience.Preempted) as ei:
            run_guarded(
                rt, pattern=4, iterations=12, config=GuardConfig(check_every=3)
            )
    finally:
        resilience.clear_preemption()
    assert ei.value.generation == 3
    assert ei.value.checkpoint_dir is None


def test_guarded_preempt_saves_audited_checkpoint(tmp_path):
    from gol_tpu.utils.guard import GuardConfig, run_guarded

    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        checkpoint_every=100,  # no cadence checkpoint before the preempt
        checkpoint_dir=str(tmp_path),
    )
    resilience.request_preemption()
    try:
        with pytest.raises(resilience.Preempted) as ei:
            run_guarded(
                rt, pattern=4, iterations=12, config=GuardConfig(check_every=3)
            )
    finally:
        resilience.clear_preemption()
    assert ei.value.generation == 3
    snap = ckpt.load(ckpt.latest(str(tmp_path)))  # fingerprint re-verified
    assert snap.generation == 3
    board0 = np.asarray(
        GolRuntime(geometry=Geometry(size=32, num_ranks=1))
        .run(pattern=4, iterations=3)[1]
        .board
    )
    np.testing.assert_array_equal(snap.board, board0)


def test_cli_preempt_exit_code_and_event(tmp_path, capsys):
    from gol_tpu import cli

    resilience.request_preemption()
    rc = cli.main(
        ["4", "32", "12", "512", "0", "--checkpoint-every", "2",
         "--checkpoint-dir", str(tmp_path / "ck"),
         "--telemetry", str(tmp_path / "tm"), "--run-id", "p"]
    )
    assert not resilience.preempt_requested()  # guard cleared it
    assert rc == resilience.EX_TEMPFAIL == 75
    assert "preempted at generation 2" in capsys.readouterr().out
    recs = [
        json.loads(ln) for ln in open(tmp_path / "tm" / "p.rank0.jsonl")
    ]
    pre = [r for r in recs if r["event"] == "preempt"]
    assert pre == [
        {**pre[0], "generation": 2, "checkpointed": True}
    ]


def test_cli_sigterm_preempts(tmp_path, capsys):
    """A real SIGTERM delivered mid-run lands on the installed handler
    and converts to the cooperative path (in-process: the signal is sent
    from a timer thread to our own pid)."""
    from gol_tpu import cli

    timer = threading.Timer(
        0.15, lambda: os.kill(os.getpid(), signal.SIGTERM)
    )
    timer.start()
    try:
        # Large enough that chunks are still running at t=0.15s.
        rc = cli.main(
            ["4", "512", "400", "512", "0", "--engine", "dense",
             "--checkpoint-every", "2",
             "--checkpoint-dir", str(tmp_path / "ck")]
        )
    finally:
        timer.cancel()
    assert rc == resilience.EX_TEMPFAIL
    out = capsys.readouterr().out
    assert "preempted at generation" in out
    path, info = resilience.resolve_auto_resume(str(tmp_path / "ck"))
    assert path is not None and info["generation"] >= 2


def test_cli3d_preempt_and_auto_resume(tmp_path, capsys):
    from gol_tpu import cli3d

    resilience.request_preemption()
    rc = cli3d.main(
        ["2", "16", "9", "64", "0", "--checkpoint-every", "3",
         "--checkpoint-dir", str(tmp_path / "ck")]
    )
    assert rc == 75
    rc = cli3d.main(
        ["2", "16", "9", "64", "1", "--checkpoint-every", "3",
         "--checkpoint-dir", str(tmp_path / "ck"), "--auto-resume",
         "--outdir", str(tmp_path / "out")]
    )
    assert rc == 0
    assert "auto-resume: generation 3" in capsys.readouterr().out
    rc = cli3d.main(
        ["2", "16", "9", "64", "1", "--outdir", str(tmp_path / "ref")]
    )
    assert rc == 0
    np.testing.assert_array_equal(
        np.load(tmp_path / "out" / "World3D_of_1.npy"),
        np.load(tmp_path / "ref" / "World3D_of_1.npy"),
    )


def test_auto_resume_iterations_are_total_target(tmp_path, capsys):
    """Relaunching the IDENTICAL argv after a preemption completes the
    remaining generations — the invariant the supervisor relies on."""
    from gol_tpu import cli
    from gol_tpu.utils import io as gol_io

    argv = ["4", "32", "12", "512", "1", "--checkpoint-every", "2",
            "--checkpoint-dir", str(tmp_path / "ck"), "--auto-resume",
            "--outdir", str(tmp_path / "out")]
    resilience.request_preemption()
    assert cli.main(argv) == 75
    assert cli.main(argv) == 0  # same argv, remaining 10 generations
    rc = cli.main(
        ["4", "32", "12", "512", "1", "--outdir", str(tmp_path / "ref")]
    )
    assert rc == 0
    name = gol_io.rank_filename(0, 1)
    assert (tmp_path / "out" / name).read_bytes() == (
        tmp_path / "ref" / name
    ).read_bytes()
    # Already at the target: a third identical launch does no work and
    # exits 0 (idempotent completion).
    assert cli.main(argv) == 0


def test_auto_resume_rejects_explicit_resume(capsys):
    from gol_tpu import cli

    rc = cli.main(
        ["4", "32", "4", "512", "0", "--auto-resume", "--resume", "x.npz"]
    )
    assert rc == 255
    assert "one of --resume/--auto-resume" in capsys.readouterr().out


def test_corrupt_plain_resume_prints_fallback_hint(tmp_path, capsys):
    from gol_tpu import cli

    rc = cli.main(
        ["4", "32", "12", "512", "0", "--checkpoint-every", "4",
         "--checkpoint-dir", str(tmp_path)]
    )
    assert rc == 0
    latest = ckpt.latest(str(tmp_path))
    _corrupt_byte(latest)
    rc = cli.main(["4", "32", "2", "512", "0", "--resume", latest])
    out = capsys.readouterr().out
    assert rc == 255
    assert "hint: an earlier valid snapshot exists at" in out
    assert "ckpt_000000000008" in out


# -- retention GC ------------------------------------------------------------


def test_gc_keeps_last_k_valid_and_protects_resume_source(tmp_path):
    board = oracle.random_board(16, 16, seed=2)
    paths = {
        g: ckpt.save(ckpt.checkpoint_path(str(tmp_path), g), board, g, 1)
        for g in (2, 4, 6, 8, 10)
    }
    deleted = resilience.gc_snapshots(
        str(tmp_path), keep=2, protect=(paths[4],)
    )
    left = [os.path.basename(p) for p in ckpt.list_snapshots(str(tmp_path))]
    assert left == [
        "ckpt_000000000004.gol.npz",  # protected resume source
        "ckpt_000000000008.gol.npz",
        "ckpt_000000000010.gol.npz",
    ]
    assert sorted(deleted) == sorted([paths[2], paths[6]])
    # Idempotent.
    assert resilience.gc_snapshots(
        str(tmp_path), keep=2, protect=(paths[4],)
    ) == []


def test_gc_never_counts_corrupt_newest_toward_k(tmp_path):
    board = oracle.random_board(16, 16, seed=3)
    for g in (2, 4, 6, 8):
        ckpt.save(ckpt.checkpoint_path(str(tmp_path), g), board, g, 1)
    _corrupt_byte(ckpt.checkpoint_path(str(tmp_path), 8))
    resilience.gc_snapshots(str(tmp_path), keep=2)
    left = [os.path.basename(p) for p in ckpt.list_snapshots(str(tmp_path))]
    # 8 is corrupt (kept as evidence, not counted); valid kept: 6, 4.
    assert left == [
        "ckpt_000000000004.gol.npz",
        "ckpt_000000000006.gol.npz",
        "ckpt_000000000008.gol.npz",
    ]


def test_gc_sweeps_writer_tmp_files(tmp_path):
    board = oracle.random_board(16, 16, seed=4)
    ckpt.save(ckpt.checkpoint_path(str(tmp_path), 2), board, 2, 1)
    tmp = ckpt.checkpoint_path(str(tmp_path), 4) + ".tmp.npz"
    with open(tmp, "wb") as f:
        f.write(b"half a snapshot")
    deleted = resilience.gc_snapshots(str(tmp_path), keep=3)
    assert tmp in deleted and not os.path.exists(tmp)


def test_runtime_gc_during_run_protects_resume_source(tmp_path):
    """keep_snapshots wired through the runtime: after a resumed run with
    checkpointing, only the newest K + the resume source remain."""
    seed_dir = tmp_path / "a"
    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        checkpoint_every=2,
        checkpoint_dir=str(seed_dir),
        keep_snapshots=2,
    )
    rt.run(pattern=4, iterations=10)
    names = [os.path.basename(p) for p in ckpt.list_snapshots(str(seed_dir))]
    assert names == [
        "ckpt_000000000008.gol.npz", "ckpt_000000000010.gol.npz"
    ]
    resume = ckpt.checkpoint_path(str(seed_dir), 8)
    rt2 = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        checkpoint_every=2,
        checkpoint_dir=str(seed_dir),
        keep_snapshots=2,
    )
    _, st = rt2.run(pattern=4, iterations=10, resume=resume)
    names = [os.path.basename(p) for p in ckpt.list_snapshots(str(seed_dir))]
    assert names == [
        "ckpt_000000000008.gol.npz",  # resume source survives the sweep
        "ckpt_000000000016.gol.npz",
        "ckpt_000000000018.gol.npz",
    ]
    np.testing.assert_array_equal(
        np.asarray(st.board), _final_board(iterations=18, size=32)
    )


# -- supervisor --------------------------------------------------------------


_FLAKY_CHILD = """
import os, sys
state = sys.argv[1]
n = int(open(state).read()) if os.path.exists(state) else 0
open(state, "w").write(str(n + 1))
attempt = os.environ.get("GOL_RESTART_ATTEMPT")
assert attempt == str(n), (attempt, n)
sys.exit(int(sys.argv[2]) if n < int(sys.argv[3]) else 0)
"""


def test_supervisor_restarts_until_success(tmp_path):
    state = str(tmp_path / "count")
    manifest = str(tmp_path / "m.json")
    rc = resilience.supervise(
        [sys.executable, "-c", _FLAKY_CHILD, state, "75", "2"],
        max_restarts=5,
        backoff_base=0.0,
        manifest_path=manifest,
        run_id="job",
    )
    assert rc == 0
    m = json.load(open(manifest))
    assert m["finished"] is True and m["final_exit"] == 0
    assert [a["exit_code"] for a in m["attempts"]] == [75, 75, 0]
    assert [a["attempt"] for a in m["attempts"]] == [0, 1, 2]
    assert all(a["pid"] for a in m["attempts"])
    assert m["run_id"] == "job"


def test_supervisor_budget_exhaustion_returns_last_code(tmp_path):
    state = str(tmp_path / "count")
    manifest = str(tmp_path / "m.json")
    rc = resilience.supervise(
        [sys.executable, "-c", _FLAKY_CHILD, state, "7", "99"],
        max_restarts=2,
        backoff_base=0.0,
        manifest_path=manifest,
    )
    assert rc == 7
    m = json.load(open(manifest))
    assert m["finished"] is False and m["final_exit"] == 7
    assert [a["exit_code"] for a in m["attempts"]] == [7, 7, 7]


def test_supervisor_records_resume_generation(tmp_path):
    board = oracle.random_board(8, 8, seed=5)
    ck = tmp_path / "ck"
    ckpt.save(ckpt.checkpoint_path(str(ck), 6), board, 6, 1)
    manifest = str(tmp_path / "m.json")
    rc = resilience.supervise(
        [sys.executable, "-c", "import sys; sys.exit(0)"],
        manifest_path=manifest,
        checkpoint_dir=str(ck),
    )
    assert rc == 0
    m = json.load(open(manifest))
    assert m["attempts"][0]["resume_generation"] == 6


def test_supervisor_module_cli(tmp_path):
    manifest = str(tmp_path / "m.json")
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu.resilience", "supervise",
         "--max-restarts", "1", "--backoff-base", "0",
         "--manifest", manifest, "--",
         sys.executable, "-c", "import sys; sys.exit(0)"],
        capture_output=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr.decode()
    assert json.load(open(manifest))["finished"] is True


def test_backoff_delay_grows_and_caps():
    import random

    rng = random.Random(0)
    d1 = resilience.supervisor.backoff_delay(1, 1.0, 60.0, rng)
    d5 = resilience.supervisor.backoff_delay(5, 1.0, 60.0, rng)
    d99 = resilience.supervisor.backoff_delay(99, 1.0, 60.0, rng)
    assert 0.5 <= d1 < 1.5
    assert 8.0 <= d5 < 24.0
    assert 30.0 <= d99 < 90.0  # capped at 60 pre-jitter
    assert resilience.supervisor.backoff_delay(3, 0.0, 60.0, rng) == 0.0


# -- resilience off is a true no-op ------------------------------------------


def test_resilience_knobs_never_change_the_traced_program(tmp_path):
    """Extends the PR 2/3 trace-identity pin: keep_snapshots,
    restart_attempt, resume_info, and an installed (undelivered)
    preemption guard leave every engine's chunk program byte-identical."""
    from gol_tpu.analysis import walker

    for engine in ("dense", "bitpack"):
        kw = dict(geometry=Geometry(size=64, num_ranks=1), engine=engine)
        rt_plain = GolRuntime(**kw)
        rt_res = GolRuntime(
            **kw,
            keep_snapshots=3,
            restart_attempt=2,
            resume_info={"generation": 4, "path": "x", "fallback": True},
        )
        spec = jax.ShapeDtypeStruct((64, 64), np.uint8)
        jaxprs = []
        with resilience.preemption_guard():
            for rt in (rt_plain, rt_res):
                fn, dynamic, static = rt._evolve_fn(4)
                jaxprs.append(
                    str(walker.trace_jaxpr(fn, spec, *dynamic, *static))
                )
        assert jaxprs[0] == jaxprs[1], f"engine {engine} trace diverged"


def test_preemption_guard_restores_handlers():
    before = (
        signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT)
    )
    with resilience.preemption_guard():
        assert signal.getsignal(signal.SIGTERM) is not before[0]
        resilience.request_preemption()
        assert resilience.preempt_requested()
    # Handlers restored, stale flag cleared.
    after = (
        signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT)
    )
    assert after == before
    assert not resilience.preempt_requested()


# -- async-writer satellites (sticky failure + tmp hygiene) ------------------


def test_writer_failure_on_final_snapshot_surfaces_at_flush(
    tmp_path, monkeypatch
):
    """The docstring claims a writer failure surfaces on flush at end of
    run; pin the nastiest case — the LAST snapshot fails, so no further
    submit() exists to raise it and only the final flush can."""
    real_save = ckpt.save
    calls = []

    def flaky(path, *a, **k):
        calls.append(path)
        if len(calls) >= 3:  # 12 iters / every 4 -> 3rd is the final one
            # Persistent (not ENOSPC, no errno): the containment layer
            # retries its bounded budget, then the error must surface.
            raise OSError("disk full at the worst moment")
        real_save(path, *a, **k)

    monkeypatch.setattr(ckpt, "save", flaky)
    rt = GolRuntime(
        geometry=Geometry(size=32, num_ranks=1),
        checkpoint_every=4,
        checkpoint_dir=str(tmp_path),
    )
    with pytest.raises(OSError, match="worst moment"):
        rt.run(pattern=4, iterations=12)
    # The final snapshot's first try plus the retry budget's attempts.
    assert len(calls) == 3 + 3
    # Snapshots before the failure are intact and verify.
    assert ckpt.verify_snapshot(ckpt.checkpoint_path(str(tmp_path), 8)) == 8


def test_killed_writer_tmp_never_resumed(tmp_path, monkeypatch):
    """A writer dying between tmp-write and rename (simulated by a
    failing os.replace) leaves only a .tmp file; latest()/latest_valid()
    must keep resolving to the previous snapshot."""
    board = oracle.random_board(16, 16, seed=6)
    p1 = ckpt.checkpoint_path(str(tmp_path), 4)
    ckpt.save(p1, board, 4, 1)

    def no_replace(src, dst):
        raise OSError("killed mid-rename")

    monkeypatch.setattr(ckpt.os, "replace", no_replace)
    w = ckpt.AsyncSnapshotWriter()
    w.submit(ckpt.save, ckpt.checkpoint_path(str(tmp_path), 8), board, 8, 1)
    with pytest.raises(OSError, match="killed"):
        w.flush()
    w.close()
    monkeypatch.undo()
    leftovers = [n for n in os.listdir(tmp_path) if n.endswith(".tmp.npz")]
    assert leftovers  # the torn write is on disk...
    assert ckpt.latest(str(tmp_path)) == p1  # ...and invisible to latest
    path, skipped = ckpt.latest_valid(str(tmp_path))
    assert path == p1 and skipped == []
    # GC sweeps the torn tmp.
    resilience.gc_snapshots(str(tmp_path), keep=3)
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp.npz")]


# -- auto-resume resolution --------------------------------------------------


def test_resolve_auto_resume_empty_and_fresh(tmp_path):
    path, info = resilience.resolve_auto_resume(str(tmp_path / "nothing"))
    assert path is None
    assert info["generation"] == -1 and info["fallback"] is False


def test_resolve_auto_resume_fallback_info(tmp_path):
    p4, p8, p12 = _make_ckpts(tmp_path)
    _corrupt_byte(p12)
    path, info = resilience.resolve_auto_resume(str(tmp_path))
    assert path == p8
    assert info["generation"] == 8 and info["fallback"] is True
    assert info["skipped"] == ["ckpt_000000000012.gol.npz"]


def test_corrupt_resume_hint(tmp_path):
    p4, p8, p12 = _make_ckpts(tmp_path)
    _corrupt_byte(p12)
    assert resilience.corrupt_resume_hint(p12) == p8
    # No valid alternative -> no hint.
    _corrupt_byte(p8)
    _corrupt_byte(p4)
    assert resilience.corrupt_resume_hint(p12) is None
