"""Bit-packed 3-D Life vs the dense life3d implementation.

The dense :mod:`gol_tpu.ops.life3d` path (separable roll-sums, itself
pinned against a brute-force neighbor count in test_life3d) is the oracle;
the packed adder tree must agree bit-for-bit for every rule and geometry,
single-device and sharded.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu.ops import bitlife3d, life3d
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import sharded3d

jax.config.update("jax_platforms", "cpu")


def _rand_vol(d, h, w, seed=0):
    return np.random.default_rng(seed).integers(0, 2, (d, h, w), np.uint8)


def _dense_run(vol, steps, rule):
    out = jnp.asarray(vol)
    for _ in range(steps):
        out = life3d.step3d(out, rule)
    return np.asarray(out)


def test_pack3d_roundtrip():
    vol = _rand_vol(3, 5, 64, seed=1)
    np.testing.assert_array_equal(
        np.asarray(bitlife3d.unpack3d(bitlife3d.pack3d(jnp.asarray(vol)))), vol
    )


@pytest.mark.parametrize("rule", [life3d.BAYS_4555, life3d.BAYS_5766])
@pytest.mark.parametrize("steps", [1, 3])
def test_packed_matches_dense(rule, steps):
    vol = _rand_vol(6, 5, 96, seed=steps + len(rule.survive))
    got = np.asarray(
        bitlife3d.evolve3d_dense_io(jnp.asarray(vol), steps, rule)
    )
    np.testing.assert_array_equal(got, _dense_run(vol, steps, rule))


def test_packed_matches_dense_dense_rule():
    """A rule with many counts exercises the full plane matcher."""
    rule = life3d.Rule3D(
        birth=frozenset({4, 5, 9, 13}), survive=frozenset({0, 2, 6, 17, 26})
    )
    vol = _rand_vol(4, 6, 64, seed=9)
    got = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 2, rule))
    np.testing.assert_array_equal(got, _dense_run(vol, 2, rule))


def test_count26_saturation():
    """A fully-alive volume: every cell has all 26 neighbors alive."""
    rule = life3d.Rule3D(birth=frozenset(), survive=frozenset({26}))
    vol = np.ones((4, 4, 32), np.uint8)
    got = np.asarray(bitlife3d.evolve3d_dense_io(jnp.asarray(vol), 1, rule))
    np.testing.assert_array_equal(got, vol)  # everyone survives on 26


def test_match_counts_rejects_overflow():
    planes = tuple(jnp.zeros((2, 2), jnp.uint32) for _ in range(5))
    with pytest.raises(ValueError, match="exceeds"):
        bitlife3d._match_counts(planes, {32})


def test_halo_full_matches_torus_step():
    vol = _rand_vol(5, 6, 64, seed=3)
    packed = bitlife3d.pack3d(jnp.asarray(vol))
    # Build the full wrap halo by hand (roll-pad each axis), words on x.
    ext = jnp.concatenate([packed[-1:], packed, packed[:1]], axis=0)
    ext = jnp.concatenate([ext[:, -1:], ext, ext[:, :1]], axis=1)
    ext = jnp.concatenate([ext[:, :, -1:], ext, ext[:, :, :1]], axis=2)
    got = bitlife3d.step3d_packed_halo_full(ext)
    np.testing.assert_array_equal(
        np.asarray(bitlife3d.unpack3d(got)), _dense_run(vol, 1, life3d.BAYS_4555)
    )


@pytest.mark.parametrize("halo_depth", [1, 2])
def test_sharded_packed_matches_dense(halo_depth):
    vol = _rand_vol(8, 8, 128, seed=4 + halo_depth)
    mesh = mesh_mod.make_mesh_3d((2, 2, 2))
    got = sharded3d.evolve_sharded3d_packed(
        jnp.asarray(vol), 5, mesh, halo_depth=halo_depth
    )
    np.testing.assert_array_equal(
        np.asarray(got), _dense_run(vol, 5, life3d.BAYS_4555)
    )


def test_sharded_packed_rejects_narrow_shards():
    vol = jnp.zeros((4, 4, 64), jnp.uint8)
    mesh = mesh_mod.make_mesh_3d((1, 2, 4))  # shard width 16 < 32
    with pytest.raises(ValueError, match="shard width"):
        sharded3d.evolve_sharded3d_packed(vol, 1, mesh)

# -- sharded 3-D flagship: fused word-tiled kernel per shard -----------------
#
# Config 5's fastest kernel composed with its decomposition (VERDICT r2
# #2): halo_depth-deep ghost plane bands over the PLANES ring + one ghost
# word column per side over the COLS ring (two-phase, corners ride the
# second hop), feeding multi_step_pallas_packed3d_wt_ext per shard.
# Interpret mode on CPU; the engine is shape-driven so the same program
# runs on chip.


def _vol3(shape=(64, 128, 256), seed=0, density=0.3):
    rng = np.random.default_rng(seed)
    return (rng.random(shape) < density).astype(np.uint8)


def _ref3(vol, steps, rule=None):
    from gol_tpu.ops import life3d

    r = jnp.asarray(vol)
    for _ in range(steps):
        r = life3d.step3d(r) if rule is None else life3d.step3d(r, rule)
    return np.asarray(r)


@pytest.mark.parametrize(
    "shape", [(2, 1, 4), (8, 1, 1), (1, 1, 8), (2, 1, 2)]
)
@pytest.mark.parametrize("steps", [8, 19])  # incl. an XLA remainder tail
def test_sharded3d_pallas_matches_oracle(shape, steps):
    n = shape[0] * shape[1] * shape[2]
    mesh = mesh_mod.make_mesh_3d(shape, devices=jax.devices()[:n])
    vol = _vol3(seed=sum(shape) + steps)
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), steps, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, steps))


@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_sharded3d_pallas_roll_dispatch_and_wt_fallback(monkeypatch):
    """r4: the sharded engine dispatches between the rolling-plane and
    word-tiled ext kernels by recompute score.  On x-unsharded meshes the
    rolling kernel carries no word ghosts, so it outscores wt whenever it
    fits; with roll knocked out the word-tiled path must still be chosen
    AND stay bit-exact (the oracle suite above otherwise only exercises
    the per-mesh winner)."""
    from gol_tpu.ops import pallas_bitlife3d

    mesh = mesh_mod.make_mesh_3d((2, 1, 1), devices=jax.devices()[:2])
    vol = _vol3((32, 128, 1024), seed=41)
    calls = {"roll": 0, "wt": 0}
    real_roll = pallas_bitlife3d.multi_step_pallas_packed3d_roll_ext
    real_wt = pallas_bitlife3d.multi_step_pallas_packed3d_wt_ext

    def spy_roll(*a, **k):
        calls["roll"] += 1
        return real_roll(*a, **k)

    def spy_wt(*a, **k):
        calls["wt"] += 1
        return real_wt(*a, **k)

    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_roll_ext", spy_roll
    )
    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_wt_ext", spy_wt
    )
    sharded3d.compiled_evolve3d_pallas.cache_clear()
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 16, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, 16))
    assert calls["roll"] and not calls["wt"]

    calls["roll"] = calls["wt"] = 0
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 0
    )
    sharded3d.compiled_evolve3d_pallas.cache_clear()
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 16, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, 16))
    assert calls["wt"] and not calls["roll"]
    sharded3d.compiled_evolve3d_pallas.cache_clear()


@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_sharded3d_pallas_ghosted_roll_dispatch(monkeypatch):
    """r4: on x-SHARDED meshes with wide shards (nw > wt's 16-word tile
    cap) the ghost-word rolling kernel outscores wt ((nw+2)/nw vs
    (tw+2)/tw) and must win; narrower shards tie and keep wt (pinned by
    the oracle suite's small meshes)."""
    from gol_tpu.ops import pallas_bitlife3d

    mesh = mesh_mod.make_mesh_3d((1, 1, 2), devices=jax.devices()[:2])
    vol = _vol3((32, 128, 2048), seed=47)  # shard nw=32, band=32, lanes=128
    calls = {"roll_g": 0, "wt": 0}
    real_g = pallas_bitlife3d.multi_step_pallas_packed3d_roll_ext_g
    real_wt = pallas_bitlife3d.multi_step_pallas_packed3d_wt_ext

    def spy_g(*a, **k):
        calls["roll_g"] += 1
        return real_g(*a, **k)

    def spy_wt(*a, **k):
        calls["wt"] += 1
        return real_wt(*a, **k)

    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_roll_ext_g", spy_g
    )
    monkeypatch.setattr(
        pallas_bitlife3d, "multi_step_pallas_packed3d_wt_ext", spy_wt
    )
    sharded3d.compiled_evolve3d_pallas.cache_clear()
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 16, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, 16))
    assert calls["roll_g"] and not calls["wt"]

    calls["roll_g"] = calls["wt"] = 0
    monkeypatch.setattr(
        pallas_bitlife3d, "pick_tile3d_roll", lambda *a, **k: 0
    )
    sharded3d.compiled_evolve3d_pallas.cache_clear()
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 16, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, 16))
    assert calls["wt"] and not calls["roll_g"]
    sharded3d.compiled_evolve3d_pallas.cache_clear()


def test_kernel_plan3d_reaches_ghosted_roll():
    """The engine's dispatch helper (factored out in r5 so the choice is
    directly assertable) picks the ghosted rolling kernel both at the
    dryrun tier (g) shard shape — 34-word x-shards of a (2,1,2) mesh,
    band extent 8, lanes 128 — and at the Hypothesis sweep's wide draw
    (17 odd words per shard: wt's only word tiling is tile_w=1)."""
    kind, tile = sharded3d.kernel_plan3d(8, 34, 128, 8, ghosted=True)
    assert kind == "roll_g" and tile >= 8
    kind, tile = sharded3d.kernel_plan3d(16, 17, 16, 8, ghosted=True)
    assert kind == "roll_g" and tile >= 8
    # x-unsharded: the plain rolling form, no word ghosts.
    kind, _ = sharded3d.kernel_plan3d(16, 32, 128, 8, ghosted=False)
    assert kind == "roll"


def test_sharded3d_pallas_ghosted_roll_real_band_ring():
    """The ghosted rolling form with a REAL band ring ((2,1,2): both the
    plane band ppermutes and the ghost-column ppermutes move data between
    devices), 32-word shards so the score dispatch picks roll_g — the
    band x column corner two-hop runs non-degenerately."""
    from gol_tpu.ops import pallas_bitlife3d

    mesh = mesh_mod.make_mesh_3d((2, 1, 2), devices=jax.devices()[:4])
    vol = _vol3((32, 128, 4096), seed=53)  # shard (16, 128, 2048): nw=64
    sharded3d.compiled_evolve3d_pallas.cache_clear()
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 16, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, 16))
    sharded3d.compiled_evolve3d_pallas.cache_clear()


def test_sharded3d_pallas_ghosted_roll_corner_crossing():
    """A live blob at the band x cols shard corner under the ghosted
    rolling kernel: the corner words must ride the two-hop exchange."""
    from gol_tpu.ops import pallas_bitlife3d

    vol = np.zeros((32, 128, 2048), np.uint8)
    rng = np.random.default_rng(11)
    # Straddle the (16, :, 1024) shard junction of a (2,1,2)-ish... here
    # (1,1,2): x junction at 1024, plus the torus x wrap at 0/2047.
    vol[:, :, 1016:1032] = (rng.random((32, 128, 16)) < 0.5).astype(np.uint8)
    vol[:, :, :8] = (rng.random((32, 128, 8)) < 0.5).astype(np.uint8)
    vol[:, :, -8:] = (rng.random((32, 128, 8)) < 0.5).astype(np.uint8)
    mesh = mesh_mod.make_mesh_3d((1, 1, 2), devices=jax.devices()[:2])
    sharded3d.compiled_evolve3d_pallas.cache_clear()
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 19, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, 19))


def test_sharded3d_pallas_deep_band_and_rule():
    from gol_tpu.ops.life3d import BAYS_5766

    mesh = mesh_mod.make_mesh_3d((2, 1, 4), devices=jax.devices()[:8])
    vol = _vol3(seed=9)
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(
            jnp.asarray(vol), 16, mesh, rule=BAYS_5766, halo_depth=16
        )
    )
    np.testing.assert_array_equal(got, _ref3(vol, 16, BAYS_5766))


def test_sharded3d_pallas_corner_crossing():
    """A live cluster at a planes×cols shard corner: the x/d corner words
    must ride the second exchange hop intact."""
    vol = np.zeros((64, 128, 256), np.uint8)
    rng = np.random.default_rng(3)
    # Dense blob straddling the (32, :, 128) shard junction of a (2,1,2)
    # mesh, spanning the packed-word boundary at x=128.
    vol[28:36, 60:68, 124:132] = (
        rng.random((8, 8, 8)) < 0.6
    ).astype(np.uint8)
    mesh = mesh_mod.make_mesh_3d((2, 1, 2), devices=jax.devices()[:4])
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 8, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, 8))


def test_sharded3d_pallas_matches_packed_tier():
    """Cross-engine: fused sharded == XLA packed sharded, same mesh."""
    mesh = mesh_mod.make_mesh_3d((2, 1, 4), devices=jax.devices()[:8])
    vol = _vol3(seed=11)
    a = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), 11, mesh)
    )
    b = np.asarray(
        sharded3d.evolve_sharded3d_packed(jnp.asarray(vol), 11, mesh)
    )
    np.testing.assert_array_equal(a, b)


def test_sharded3d_pallas_rejections():
    mesh_rows = mesh_mod.make_mesh_3d((2, 2, 2), devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="H-unsharded"):
        sharded3d.compiled_evolve3d_pallas(mesh_rows, 8)
    mesh = mesh_mod.make_mesh_3d((2, 1, 2), devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="multiple of 8"):
        sharded3d.compiled_evolve3d_pallas(mesh, 8, halo_depth=4)
    with pytest.raises(ValueError, match="light cone"):
        sharded3d.compiled_evolve3d_pallas(mesh, 40, halo_depth=40)
    # Shard depth below the exchanged plane band.
    shallow = _vol3((8, 128, 128), seed=1)
    mesh8 = mesh_mod.make_mesh_3d((8, 1, 1), devices=jax.devices()[:8])
    with pytest.raises(Exception, match="exchanged band"):
        np.asarray(
            sharded3d.evolve_sharded3d_pallas(
                jnp.asarray(shallow), 8, mesh8
            )
        )


@pytest.mark.parametrize("shape", [(1, 2, 4), (1, 8, 1), (1, 4, 2)])
@pytest.mark.parametrize("steps", [8, 19])
def test_sharded3d_pallas_h_sharded_transposed_layout(shape, steps):
    """planes == 1 meshes run the transposed kernel layout (band over the
    ROWS ring, lanes = the unsharded D axis) — same kernel, axes
    relabeled; byte-equality against the dense oracle."""
    n = shape[0] * shape[1] * shape[2]
    mesh = mesh_mod.make_mesh_3d(shape, devices=jax.devices()[:n])
    vol = _vol3((128, 64, 256), seed=100 + sum(shape) + steps)
    got = np.asarray(
        sharded3d.evolve_sharded3d_pallas(jnp.asarray(vol), steps, mesh)
    )
    np.testing.assert_array_equal(got, _ref3(vol, steps))


def test_sharded3d_pallas_rejects_doubly_sharded_spatial_axes():
    mesh = mesh_mod.make_mesh_3d((2, 2, 2), devices=jax.devices()[:8])
    with pytest.raises(ValueError, match=r"\(P,1,C\) or \(1,R,C\)"):
        sharded3d.compiled_evolve3d_pallas(mesh, 8)
