"""Checkpoint/resume: snapshot round-trip and resumed-run equivalence."""

import numpy as np
import pytest

from gol_tpu.models.state import Geometry
from gol_tpu.runtime import GolRuntime
from gol_tpu.utils import checkpoint as ckpt

from tests import oracle


def test_save_load_roundtrip(tmp_path):
    board = np.random.default_rng(0).integers(0, 2, (16, 8)).astype(np.uint8)
    path = ckpt.checkpoint_path(str(tmp_path), 42)
    ckpt.save(path, board, 42, num_ranks=2)
    snap = ckpt.load(path)
    np.testing.assert_array_equal(snap.board, board)
    assert snap.generation == 42 and snap.num_ranks == 2
    assert snap.top0 is None and snap.bottom0 is None


def test_save_load_with_frozen_halos(tmp_path):
    board = np.random.default_rng(1).integers(0, 2, (16, 8)).astype(np.uint8)
    top0 = board[::8].copy()  # [2, 8] — one row per rank
    bottom0 = board[7::8].copy()
    path = ckpt.checkpoint_path(str(tmp_path), 7)
    ckpt.save(path, board, 7, num_ranks=2, top0=top0, bottom0=bottom0)
    snap = ckpt.load(path)
    np.testing.assert_array_equal(snap.top0, top0)
    np.testing.assert_array_equal(snap.bottom0, bottom0)


def test_latest_picks_highest_generation(tmp_path):
    b = np.zeros((4, 4), np.uint8)
    for g in (5, 100, 20):
        ckpt.save(ckpt.checkpoint_path(str(tmp_path), g), b, g, 1)
    assert ckpt.latest(str(tmp_path)).endswith("ckpt_000000000100.gol.npz")
    assert ckpt.latest(str(tmp_path / "missing")) is None


def test_runtime_checkpoints_and_resume_equivalence(tmp_path):
    """10 straight generations == 4 generations, checkpoint, resume +6."""
    geom = Geometry(size=8, num_ranks=1)
    straight = GolRuntime(geometry=geom)
    _, st_straight = straight.run(pattern=4, iterations=10)
    final_straight = st_straight.board

    ck_dir = str(tmp_path / "ck")
    part1 = GolRuntime(geometry=geom, checkpoint_every=4, checkpoint_dir=ck_dir)
    part1.run(pattern=4, iterations=4)
    resume_path = ckpt.latest(ck_dir)
    assert resume_path is not None

    part2 = GolRuntime(geometry=geom)
    _, st_resumed = part2.run(pattern=4, iterations=6, resume=resume_path)
    final_resumed = st_resumed.board
    np.testing.assert_array_equal(np.asarray(final_resumed), np.asarray(final_straight))


def test_stale_t0_chunked_and_resumed_keeps_original_halos(tmp_path):
    """Regression: a chunked/resumed stale_t0 (reference-compat) run must
    keep the t=0 frozen halos — re-freezing per chunk silently changes the
    semantics (halos must stay at true t=0 per bug B1)."""
    size, ranks, iters = 8, 3, 6
    geom = Geometry(size=size, num_ranks=ranks)
    board0 = np.random.default_rng(7).integers(0, 2, (ranks * size, size))
    board0 = board0.astype(np.uint8)
    expected = oracle.simulate_reference(board0, ranks, iters)

    ck_dir = str(tmp_path / "ck")
    # Chunked run (checkpoint every 2 gens) from a custom t=0 board: seed the
    # runtime via a handcrafted snapshot so we control the board exactly.
    seed_path = ckpt.checkpoint_path(str(tmp_path), 0)
    from gol_tpu.parallel import engine as engine_mod
    import jax.numpy as jnp

    top0, bottom0 = engine_mod.frozen_halos(jnp.asarray(board0), ranks)
    ckpt.save(
        seed_path, board0, 0, ranks, top0=np.asarray(top0), bottom0=np.asarray(bottom0)
    )
    rt = GolRuntime(
        geometry=geom,
        halo_mode="stale_t0",
        checkpoint_every=2,
        checkpoint_dir=ck_dir,
    )
    rt.run(pattern=0, iterations=4, resume=seed_path)
    # Resume the last 2 gens in a fresh runtime from the gen-4 snapshot.
    rt2 = GolRuntime(geometry=geom, halo_mode="stale_t0")
    _, st_final = rt2.run(pattern=0, iterations=2, resume=ckpt.latest(ck_dir))
    assert int(st_final.generation) == iters
    np.testing.assert_array_equal(np.asarray(st_final.board), expected)


def test_stale_t0_resume_without_halos_rejected(tmp_path):
    path = ckpt.checkpoint_path(str(tmp_path), 3)
    ckpt.save(path, np.zeros((8, 8), np.uint8), 3, num_ranks=1)
    rt = GolRuntime(geometry=Geometry(size=8, num_ranks=1), halo_mode="stale_t0")
    with pytest.raises(ValueError, match="frozen halos"):
        rt.run(pattern=0, iterations=1, resume=path)


def test_resume_geometry_mismatch_rejected(tmp_path):
    path = ckpt.checkpoint_path(str(tmp_path), 1)
    ckpt.save(path, np.zeros((16, 8), np.uint8), 1, num_ranks=2)
    rt = GolRuntime(geometry=Geometry(size=8, num_ranks=1))
    with pytest.raises(ValueError, match="ranks"):
        rt.run(pattern=0, iterations=1, resume=path)


def test_runtime_report_phases(tmp_path):
    geom = Geometry(size=8, num_ranks=1)
    report, state = GolRuntime(geometry=geom).run(pattern=4, iterations=2)
    assert report.cell_updates == 8 * 8 * 2
    assert {"init", "compile", "total"} <= set(report.phases)
    assert report.duration_line().startswith("TOTAL DURATION : ")
    assert state.board.shape == (8, 8)
    assert int(state.generation) == 2
