"""Checkpoint/resume: snapshot round-trip and resumed-run equivalence."""

import numpy as np
import pytest

from gol_tpu.models.state import Geometry
from gol_tpu.runtime import GolRuntime
from gol_tpu.utils import checkpoint as ckpt

from tests import oracle


def test_save_load_roundtrip(tmp_path):
    board = np.random.default_rng(0).integers(0, 2, (16, 8)).astype(np.uint8)
    path = ckpt.checkpoint_path(str(tmp_path), 42)
    ckpt.save(path, board, 42, num_ranks=2)
    snap = ckpt.load(path)
    np.testing.assert_array_equal(snap.board, board)
    assert snap.generation == 42 and snap.num_ranks == 2
    assert snap.top0 is None and snap.bottom0 is None


def test_save_load_with_frozen_halos(tmp_path):
    board = np.random.default_rng(1).integers(0, 2, (16, 8)).astype(np.uint8)
    top0 = board[::8].copy()  # [2, 8] — one row per rank
    bottom0 = board[7::8].copy()
    path = ckpt.checkpoint_path(str(tmp_path), 7)
    ckpt.save(path, board, 7, num_ranks=2, top0=top0, bottom0=bottom0)
    snap = ckpt.load(path)
    np.testing.assert_array_equal(snap.top0, top0)
    np.testing.assert_array_equal(snap.bottom0, bottom0)


def test_latest_picks_highest_generation(tmp_path):
    b = np.zeros((4, 4), np.uint8)
    for g in (5, 100, 20):
        ckpt.save(ckpt.checkpoint_path(str(tmp_path), g), b, g, 1)
    assert ckpt.latest(str(tmp_path)).endswith("ckpt_000000000100.gol.npz")
    assert ckpt.latest(str(tmp_path / "missing")) is None


def test_runtime_checkpoints_and_resume_equivalence(tmp_path):
    """10 straight generations == 4 generations, checkpoint, resume +6."""
    geom = Geometry(size=8, num_ranks=1)
    straight = GolRuntime(geometry=geom)
    _, st_straight = straight.run(pattern=4, iterations=10)
    final_straight = st_straight.board

    ck_dir = str(tmp_path / "ck")
    part1 = GolRuntime(geometry=geom, checkpoint_every=4, checkpoint_dir=ck_dir)
    part1.run(pattern=4, iterations=4)
    resume_path = ckpt.latest(ck_dir)
    assert resume_path is not None

    part2 = GolRuntime(geometry=geom)
    _, st_resumed = part2.run(pattern=4, iterations=6, resume=resume_path)
    final_resumed = st_resumed.board
    np.testing.assert_array_equal(np.asarray(final_resumed), np.asarray(final_straight))


def test_stale_t0_chunked_and_resumed_keeps_original_halos(tmp_path):
    """Regression: a chunked/resumed stale_t0 (reference-compat) run must
    keep the t=0 frozen halos — re-freezing per chunk silently changes the
    semantics (halos must stay at true t=0 per bug B1)."""
    size, ranks, iters = 8, 3, 6
    geom = Geometry(size=size, num_ranks=ranks)
    board0 = np.random.default_rng(7).integers(0, 2, (ranks * size, size))
    board0 = board0.astype(np.uint8)
    expected = oracle.simulate_reference(board0, ranks, iters)

    ck_dir = str(tmp_path / "ck")
    # Chunked run (checkpoint every 2 gens) from a custom t=0 board: seed the
    # runtime via a handcrafted snapshot so we control the board exactly.
    seed_path = ckpt.checkpoint_path(str(tmp_path), 0)
    from gol_tpu.parallel import engine as engine_mod
    import jax.numpy as jnp

    top0, bottom0 = engine_mod.frozen_halos(jnp.asarray(board0), ranks)
    ckpt.save(
        seed_path, board0, 0, ranks, top0=np.asarray(top0), bottom0=np.asarray(bottom0)
    )
    rt = GolRuntime(
        geometry=geom,
        halo_mode="stale_t0",
        checkpoint_every=2,
        checkpoint_dir=ck_dir,
    )
    rt.run(pattern=0, iterations=4, resume=seed_path)
    # Resume the last 2 gens in a fresh runtime from the gen-4 snapshot.
    rt2 = GolRuntime(geometry=geom, halo_mode="stale_t0")
    _, st_final = rt2.run(pattern=0, iterations=2, resume=ckpt.latest(ck_dir))
    assert int(st_final.generation) == iters
    np.testing.assert_array_equal(np.asarray(st_final.board), expected)


def test_stale_t0_resume_without_halos_rejected(tmp_path):
    path = ckpt.checkpoint_path(str(tmp_path), 3)
    ckpt.save(path, np.zeros((8, 8), np.uint8), 3, num_ranks=1)
    rt = GolRuntime(geometry=Geometry(size=8, num_ranks=1), halo_mode="stale_t0")
    with pytest.raises(ValueError, match="frozen halos"):
        rt.run(pattern=0, iterations=1, resume=path)


def test_resume_geometry_mismatch_rejected(tmp_path):
    path = ckpt.checkpoint_path(str(tmp_path), 1)
    ckpt.save(path, np.zeros((16, 8), np.uint8), 1, num_ranks=2)
    rt = GolRuntime(geometry=Geometry(size=8, num_ranks=1))
    with pytest.raises(ValueError, match="ranks"):
        rt.run(pattern=0, iterations=1, resume=path)


def test_runtime_report_phases(tmp_path):
    geom = Geometry(size=8, num_ranks=1)
    report, state = GolRuntime(geometry=geom).run(pattern=4, iterations=2)
    assert report.cell_updates == 8 * 8 * 2
    assert {"init", "compile", "total"} <= set(report.phases)
    assert report.duration_line().startswith("TOTAL DURATION : ")
    assert state.board.shape == (8, 8)
    assert int(state.generation) == 2


# -- sharded checkpoints (per-host pieces + manifest, VERDICT r1 #4) ---------


def _sharded_board(shape=(32, 64), mesh_shape=(2, 2), seed=0):
    import jax
    import jax.numpy as jnp

    from gol_tpu.parallel import mesh as mesh_mod

    board = oracle.random_board(*shape, seed=seed)
    mesh = mesh_mod.make_mesh_2d(
        mesh_shape, devices=jax.devices()[: mesh_shape[0] * mesh_shape[1]]
    )
    arr = jax.device_put(
        jnp.asarray(board), mesh_mod.board_sharding(mesh)
    )
    return board, arr, mesh


def test_sharded_save_load_roundtrip(tmp_path):
    board, arr, mesh = _sharded_board()
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 17)
    ckpt.save_sharded(d, arr, 17, num_ranks=4)
    meta = ckpt.load_sharded_meta(d)
    assert meta.generation == 17 and meta.num_ranks == 4
    assert meta.shape == board.shape and meta.rule is None
    assert len(meta.rects) == 4  # one piece per 2x2 shard
    full = ckpt.read_sharded_region(
        d, meta, (slice(None), slice(None))
    )
    np.testing.assert_array_equal(full, board)
    # Partial reads assemble any region, crossing piece boundaries.
    part = ckpt.read_sharded_region(d, meta, (slice(10, 30), slice(16, 48)))
    np.testing.assert_array_equal(part, board[10:30, 16:48])


def test_sharded_piece_fingerprints_sum_to_global(tmp_path):
    from gol_tpu.utils.guard import fingerprint_np

    board, arr, _ = _sharded_board(seed=3)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 1)
    ckpt.save_sharded(
        d, arr, 1, num_ranks=1, fingerprint=fingerprint_np(board)
    )
    # load_sharded_meta verifies sum(piece fps) == stamped global fp.
    meta = ckpt.load_sharded_meta(d)
    assert meta.fingerprint == fingerprint_np(board)


def test_sharded_global_stamp_mismatch_rejected(tmp_path):
    board, arr, _ = _sharded_board(seed=4)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 1)
    ckpt.save_sharded(d, arr, 1, num_ranks=1, fingerprint=0xDEADBEEF)
    with pytest.raises(ckpt.CorruptSnapshotError, match="fingerprints sum"):
        ckpt.load_sharded_meta(d)


def test_sharded_corrupt_piece_rejected(tmp_path):
    import os

    board, arr, _ = _sharded_board(seed=5)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 9)
    ckpt.save_sharded(d, arr, 9, num_ranks=4)
    # Corrupt one piece in the (single-process) shards file, keeping its
    # stored fingerprint: the per-piece verification must trip on read.
    path = os.path.join(d, "shards_00000.npz")
    with np.load(path) as data:
        arrays = {k: data[k].copy() for k in data.files}
    arrays["piece_0"] = arrays["piece_0"].copy()
    arrays["piece_0"][0, 0] ^= 1  # a VALID cell value — in-range flip
    np.savez_compressed(path, **arrays)
    meta = ckpt.load_sharded_meta(d)
    with pytest.raises(ckpt.CorruptSnapshotError, match="fingerprint"):
        ckpt.read_sharded_region(d, meta, (slice(None), slice(None)))


def test_latest_finds_sharded_dirs(tmp_path):
    b = np.zeros((4, 4), np.uint8)
    ckpt.save(ckpt.checkpoint_path(str(tmp_path), 5), b, 5, 1)
    _, arr, _ = _sharded_board(seed=6)
    ckpt.save_sharded(
        ckpt.sharded_checkpoint_path(str(tmp_path), 40), arr, 40, 1
    )
    assert ckpt.latest(str(tmp_path)).endswith("ckpt_000000000040.gol.d")


def test_runtime_resumes_from_sharded_checkpoint(tmp_path):
    """Straight run == run to gen 4, sharded save, sharded resume +6 —
    both on the mesh (make_array_from_callback path) and single-device."""
    import jax

    from gol_tpu.parallel import mesh as mesh_mod

    geom = Geometry(size=16, num_ranks=4)  # 64x16 world
    mesh = mesh_mod.make_mesh_1d(4)
    straight = GolRuntime(geometry=geom, mesh=mesh)
    _, st_straight = straight.run(pattern=4, iterations=10)

    part1 = GolRuntime(geometry=geom, mesh=mesh)
    _, st4 = part1.run(pattern=4, iterations=4)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 4)
    ckpt.save_sharded(d, st4.board, 4, num_ranks=4)

    part2 = GolRuntime(geometry=geom, mesh=mesh)
    _, st_resumed = part2.run(pattern=4, iterations=6, resume=d)
    np.testing.assert_array_equal(
        np.asarray(st_resumed.board), np.asarray(st_straight.board)
    )
    # Single-device resume from the same sharded checkpoint.
    part3 = GolRuntime(geometry=geom)
    _, st_resumed1 = part3.run(pattern=4, iterations=6, resume=d)
    np.testing.assert_array_equal(
        np.asarray(st_resumed1.board), np.asarray(st_straight.board)
    )


def test_sharded_resume_mismatches_rejected(tmp_path):
    _, arr, _ = _sharded_board(shape=(128, 64), seed=7)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 2)
    ckpt.save_sharded(d, arr, 2, num_ranks=2, rule="B36/S23")
    with pytest.raises(ValueError, match="ranks"):
        GolRuntime(geometry=Geometry(size=64, num_ranks=4)).initial_state(
            0, resume=d
        )
    with pytest.raises(ValueError, match="B36/S23"):
        GolRuntime(geometry=Geometry(size=64, num_ranks=2)).initial_state(
            0, resume=d
        )


def test_latest_skips_torn_sharded_dirs(tmp_path):
    """A crash mid-save leaves a sharded dir without its manifest or with
    missing shard files; latest() must keep returning the older complete
    snapshot, never the torn one."""
    import os

    _, arr, _ = _sharded_board(seed=8)
    good = ckpt.sharded_checkpoint_path(str(tmp_path), 40)
    ckpt.save_sharded(good, arr, 40, 1)
    # Torn dir 1: no manifest at all.
    os.makedirs(ckpt.sharded_checkpoint_path(str(tmp_path), 50))
    assert ckpt.latest(str(tmp_path)) == good
    # Torn dir 2: manifest present but a referenced shard file is missing.
    torn = ckpt.sharded_checkpoint_path(str(tmp_path), 60)
    ckpt.save_sharded(torn, arr, 60, 1)
    os.remove(os.path.join(torn, "shards_00000.npz"))
    assert ckpt.latest(str(tmp_path)) == good

def test_sharded_overlapping_manifest_rejected(tmp_path):
    """Overlapping rects whose areas still sum to h*w must be rejected at
    load — otherwise read_sharded_region double-counts the overlap and can
    report a region complete while leaving uncovered cells as np.empty
    garbage (ADVICE r2)."""
    import os

    _, arr, _ = _sharded_board(seed=9)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 3)
    ckpt.save_sharded(d, arr, 3, num_ranks=4)
    mpath = os.path.join(d, "manifest.npz")
    with np.load(mpath) as data:
        arrays = {k: data[k].copy() for k in data.files}
    h, w = (int(x) for x in arrays["shape"])
    # Two half-board rects shifted to overlap: total area == h*w but the
    # right quarter of the board is uncovered.
    arrays["rects"] = np.asarray(
        [(0, h, 0, w // 2), (0, h, w // 4, 3 * w // 4)], np.int64
    )
    arrays["procs"] = np.asarray([0, 0], np.int64)
    np.savez_compressed(mpath, **arrays)
    with pytest.raises(ckpt.CorruptSnapshotError, match="overlap"):
        ckpt.load_sharded_meta(d)


def test_sharded_out_of_bounds_manifest_rejected(tmp_path):
    import os

    _, arr, _ = _sharded_board(seed=10)
    d = ckpt.sharded_checkpoint_path(str(tmp_path), 3)
    ckpt.save_sharded(d, arr, 3, num_ranks=4)
    mpath = os.path.join(d, "manifest.npz")
    with np.load(mpath) as data:
        arrays = {k: data[k].copy() for k in data.files}
    h, w = (int(x) for x in arrays["shape"])
    arrays["rects"] = np.asarray(
        [(0, h, 0, w), (h, h + 1, 0, w)], np.int64
    )
    arrays["procs"] = np.asarray([0, 0], np.int64)
    np.savez_compressed(mpath, **arrays)
    with pytest.raises(ckpt.CorruptSnapshotError, match="outside"):
        ckpt.load_sharded_meta(d)


def test_chunk_schedule_rejects_zero_chunk():
    """chunk_schedule is shared public policy; chunk=0 with work to do must
    error, not hang (ADVICE r2)."""
    from gol_tpu.runtime import chunk_schedule

    with pytest.raises(ValueError, match="chunk"):
        chunk_schedule(10, 0)
    assert chunk_schedule(0, 0) == []
    assert chunk_schedule(10, 4) == [4, 4, 2]
    assert chunk_schedule(3, 100) == [3]

# -- sharded 3-D checkpoints -------------------------------------------------


def _sharded_volume(shape=(16, 32, 64), mesh_shape=(2, 1, 2), seed=0):
    import jax
    import jax.numpy as jnp

    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import sharded3d

    rng = np.random.default_rng(seed)
    vol = (rng.random(shape) < 0.3).astype(np.uint8)
    n = mesh_shape[0] * mesh_shape[1] * mesh_shape[2]
    mesh = mesh_mod.make_mesh_3d(mesh_shape, devices=jax.devices()[:n])
    arr = jax.device_put(
        jnp.asarray(vol), sharded3d.volume_sharding(mesh)
    )
    return vol, arr, mesh


def test_sharded3d_save_load_roundtrip(tmp_path):
    vol, arr, _ = _sharded_volume()
    d = ckpt.sharded_checkpoint3d_path(str(tmp_path), 9)
    ckpt.save_sharded3d(d, arr, 9, "B5/S4,5")
    meta = ckpt.load_sharded3d_meta(d)
    assert meta.generation == 9 and meta.rule == "B5/S4,5"
    assert meta.shape == vol.shape and len(meta.boxes) == 4
    full = ckpt.read_sharded3d_region(
        d, meta, (slice(None), slice(None), slice(None))
    )
    np.testing.assert_array_equal(full, vol)
    part = ckpt.read_sharded3d_region(
        d, meta, (slice(4, 12), slice(10, 30), slice(16, 48))
    )
    np.testing.assert_array_equal(part, vol[4:12, 10:30, 16:48])


def test_sharded3d_global_stamp_additivity(tmp_path):
    """Piece stamps sum to the [D*H, W]-flattened volume fingerprint —
    the invariant letting a global stamp verify with no assembly."""
    from gol_tpu.utils.checkpoint import _vol_fingerprint

    vol, arr, _ = _sharded_volume(seed=3)
    d = ckpt.sharded_checkpoint3d_path(str(tmp_path), 1)
    ckpt.save_sharded3d(d, arr, 1, "B5/S4,5", fingerprint=_vol_fingerprint(vol))
    meta = ckpt.load_sharded3d_meta(d)  # verifies sum(piece fps) == stamp
    assert meta.fingerprint == _vol_fingerprint(vol)
    # And the stamp matches the 3-D device audit's fingerprint.
    from gol_tpu.utils.guard import audit_board

    import jax.numpy as jnp

    assert audit_board(jnp.asarray(vol)).fingerprint == meta.fingerprint


def test_sharded3d_corrupt_piece_rejected(tmp_path):
    import os

    vol, arr, _ = _sharded_volume(seed=5)
    d = ckpt.sharded_checkpoint3d_path(str(tmp_path), 2)
    ckpt.save_sharded3d(d, arr, 2, "B5/S4,5")
    path = os.path.join(d, "shards_00000.npz")
    with np.load(path) as data:
        arrays = {k: data[k].copy() for k in data.files}
    arrays["piece_0"][0, 0, 0] ^= 1  # in-range flip
    np.savez_compressed(path, **arrays)
    meta = ckpt.load_sharded3d_meta(d)
    with pytest.raises(ckpt.CorruptSnapshotError, match="fingerprint"):
        ckpt.read_sharded3d_region(
            d, meta, (slice(None), slice(None), slice(None))
        )


def test_sharded3d_bad_manifest_rejected(tmp_path):
    import os

    vol, arr, _ = _sharded_volume(seed=6)
    d = ckpt.sharded_checkpoint3d_path(str(tmp_path), 2)
    ckpt.save_sharded3d(d, arr, 2, "B5/S4,5")
    mpath = os.path.join(d, "manifest.npz")
    with np.load(mpath) as data:
        arrays = {k: data[k].copy() for k in data.files}
    dd, hh, ww = (int(x) for x in arrays["shape"])
    # Overlapping boxes summing to the volume (uncovered right half).
    arrays["boxes"] = np.asarray(
        [
            (0, dd, 0, hh, 0, ww // 2),
            (0, dd, 0, hh, ww // 4, 3 * ww // 4),
        ],
        np.int64,
    )
    arrays["procs"] = np.asarray([0, 0], np.int64)
    np.savez_compressed(mpath, **arrays)
    with pytest.raises(ckpt.CorruptSnapshotError, match="overlap"):
        ckpt.load_sharded3d_meta(d)


# -- async checkpoint writer (r4): overlap file I/O with device compute ------


def test_async_checkpointing_end_to_end(tmp_path):
    """run() with checkpoint_every uses the background writer; after the
    final flush every snapshot is durably renamed and loadable, and the
    run result is unchanged."""
    from gol_tpu.models import patterns

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        checkpoint_every=4,
        checkpoint_dir=str(tmp_path),
    )
    _, state = rt.run(pattern=4, iterations=12)
    assert rt._ckpt_writer is None  # lifecycle ended with the run
    snaps = sorted(tmp_path.glob("ckpt_*" + ckpt.CKPT_SUFFIX))
    assert len(snaps) == 3
    assert not list(tmp_path.glob("*.tmp.npz"))  # no torn writes left
    board0 = patterns.init_global(4, 64, 1)
    for i, path in enumerate(snaps):
        snap = ckpt.load(str(path))
        assert snap.generation == 4 * (i + 1)
        np.testing.assert_array_equal(
            snap.board, oracle.run_torus(board0, snap.generation)
        )
    np.testing.assert_array_equal(
        np.asarray(state.board), ckpt.load(str(snaps[-1])).board
    )


def test_async_writer_failure_surfaces_and_keeps_previous(
    tmp_path, monkeypatch
):
    """A background write failure is sticky: the run raises (at the next
    submit or the final flush) instead of finishing with silently missing
    snapshots, and the snapshots written before the failure are intact."""
    real_save = ckpt.save
    written = []

    def flaky(path, *a, **k):
        if written:
            raise OSError("disk full")
        written.append(path)
        real_save(path, *a, **k)

    monkeypatch.setattr(ckpt, "save", flaky)
    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        checkpoint_every=4,
        checkpoint_dir=str(tmp_path),
    )
    # OSError keeps its type across the thread hop — the CLIs' clean-exit
    # handlers catch (ValueError, OSError) and must keep doing so.
    with pytest.raises(OSError, match="disk full"):
        rt.run(pattern=4, iterations=12)
    snap = ckpt.load(written[0])
    assert snap.generation == 4  # the pre-failure snapshot survived


def test_crash_mid_write_leaves_previous_snapshot(tmp_path, monkeypatch):
    """A crash between the tmp write and the rename (simulated by a
    failing os.replace) leaves the previous snapshot loadable and never
    exposes a torn file at the snapshot path."""
    import os

    board = oracle.random_board(16, 32, seed=5)
    p1 = ckpt.checkpoint_path(str(tmp_path), 4)
    ckpt.save(p1, board, 4, 1)

    def no_replace(src, dst):
        raise OSError("power cut")

    monkeypatch.setattr(ckpt.os, "replace", no_replace)
    w = ckpt.AsyncSnapshotWriter()
    p2 = ckpt.checkpoint_path(str(tmp_path), 8)
    w.submit(ckpt.save, p2, board, 8, 1)
    with pytest.raises(OSError, match="power cut"):
        w.flush()
    w.close()
    assert not os.path.exists(p2)  # never a torn snapshot at the path
    monkeypatch.undo()
    snap = ckpt.load(p1)
    assert snap.generation == 4
    np.testing.assert_array_equal(snap.board, board)


def test_guarded_run_uses_async_writer(tmp_path):
    """run_guarded shares the writer lifecycle: snapshots from the
    audited loop are complete and fingerprint-stamped after the flush."""
    from gol_tpu.models import patterns
    from gol_tpu.utils.guard import GuardConfig, run_guarded

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        checkpoint_every=4,
        checkpoint_dir=str(tmp_path),
    )
    _, state, guard = run_guarded(
        rt, pattern=4, iterations=8, config=GuardConfig(check_every=4)
    )
    snaps = sorted(tmp_path.glob("ckpt_*" + ckpt.CKPT_SUFFIX))
    assert len(snaps) == 2
    board0 = patterns.init_global(4, 64, 1)
    last = ckpt.load(str(snaps[-1]))  # load re-verifies the fingerprint
    np.testing.assert_array_equal(last.board, oracle.run_torus(board0, 8))
