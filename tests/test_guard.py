"""Failure-detection + elastic-recovery tests (utils/guard.py).

The recovery path is exercised for real via fault injection — corrupted
boards must be detected, rolled back, and replayed to the exact result an
unfaulted run produces.  Snapshot integrity (fingerprint verification on
load) is drilled by tampering with a written checkpoint.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu.models.state import Geometry
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.runtime import GolRuntime
from gol_tpu.utils import checkpoint as ckpt_mod
from gol_tpu.utils import guard

jax.config.update("jax_platforms", "cpu")


def _rand_board(h, w, seed=0):
    return np.random.default_rng(seed).integers(0, 2, (h, w), dtype=np.uint8)


# -- detection ---------------------------------------------------------------


def test_fingerprint_device_matches_numpy():
    board = _rand_board(37, 53, seed=1)
    audit = guard.audit_board(jnp.asarray(board))
    assert audit.fingerprint == guard.fingerprint_np(board)
    assert audit.ok
    assert audit.population == int(board.sum())
    assert audit.max_cell == int(board.max())


def test_fingerprint_sensitive_to_any_cell_flip():
    board = _rand_board(16, 16, seed=2)
    base = guard.fingerprint_np(board)
    for (i, j) in [(0, 0), (7, 3), (15, 15)]:
        flipped = board.copy()
        flipped[i, j] ^= 1
        assert guard.fingerprint_np(flipped) != base


def test_fingerprint_position_sensitive():
    a = np.zeros((8, 8), np.uint8)
    b = np.zeros((8, 8), np.uint8)
    a[1, 2] = 1
    b[2, 1] = 1
    assert guard.fingerprint_np(a) != guard.fingerprint_np(b)


def test_fingerprint_chunking_invariant():
    # The row-chunked loop must agree with a one-shot computation.
    board = _rand_board(300, 70, seed=3)
    whole = guard.fingerprint_np(board)
    ri = (np.arange(300, dtype=np.uint32) * np.uint32(0x9E3779B1) + 1)[:, None]
    cj = (np.arange(70, dtype=np.uint32) * np.uint32(0x85EBCA77) + 1)[None, :]
    with np.errstate(over="ignore"):
        w = np.uint32(1) + ri * cj * np.uint32(0xC2B2AE35)
        ref = int(np.sum(board.astype(np.uint32) * w, dtype=np.uint32))
    assert whole == ref


def test_audit_detects_out_of_range_cell():
    board = jnp.asarray(_rand_board(16, 16, seed=4))
    bad = guard.inject_bitflip(board, 3, 5)
    audit = guard.audit_board(bad, generation=7)
    assert not audit.ok
    assert audit.max_cell == 0xA5
    assert audit.generation == 7


def test_audit_on_sharded_board():
    mesh = mesh_mod.make_mesh_2d()
    board = _rand_board(32, 16, seed=5)
    sharded = jax.device_put(board, mesh_mod.board_sharding(mesh))
    audit = guard.audit_board(sharded)
    assert audit.fingerprint == guard.fingerprint_np(board)


# -- elastic recovery --------------------------------------------------------


def _run_plain(geom, pattern, iterations, **kw):
    rt = GolRuntime(geometry=geom, **kw)
    _, state = rt.run(pattern=pattern, iterations=iterations)
    return np.asarray(state.board)


@pytest.mark.parametrize("iterations,check_every", [(10, 3), (8, 8), (5, 1)])
def test_guarded_no_fault_matches_unguarded(iterations, check_every):
    geom = Geometry(size=16, num_ranks=2)
    rt = GolRuntime(geometry=geom)
    report, state, greport = guard.run_guarded(
        rt, 4, iterations, guard.GuardConfig(check_every=check_every)
    )
    expected = _run_plain(geom, 4, iterations)
    np.testing.assert_array_equal(np.asarray(state.board), expected)
    assert greport.failures == 0
    assert greport.restores == 0
    assert greport.checks == -(-iterations // check_every)
    assert int(state.generation) == iterations
    assert report.cell_updates == geom.cell_updates(iterations)


def test_transient_fault_detected_and_recovered():
    geom = Geometry(size=16, num_ranks=2)
    fired = []

    def fault_once(board, generation):
        if generation == 6 and not fired:
            fired.append(generation)
            return guard.inject_bitflip(board, 2, 2)
        return board

    rt = GolRuntime(geometry=geom)
    _, state, greport = guard.run_guarded(
        rt, 4, 10, guard.GuardConfig(check_every=3, fault_hook=fault_once)
    )
    # Replay after rollback converges to the exact unfaulted result.
    np.testing.assert_array_equal(
        np.asarray(state.board), _run_plain(geom, 4, 10)
    )
    assert greport.failures == 1
    assert greport.restores == 1
    assert fired == [6]


def test_persistent_fault_exhausts_budget():
    geom = Geometry(size=16, num_ranks=1)

    def always_corrupt(board, generation):
        return guard.inject_bitflip(board, 0, 0)

    rt = GolRuntime(geometry=geom)
    with pytest.raises(guard.GuardError, match="restore budget"):
        guard.run_guarded(
            rt,
            4,
            6,
            guard.GuardConfig(
                check_every=2, max_restores=2, fault_hook=always_corrupt
            ),
        )


def test_guarded_sharded_run_matches_unguarded():
    geom = Geometry(size=16, num_ranks=4)
    mesh = mesh_mod.make_mesh_1d()
    rt = GolRuntime(geometry=geom, mesh=mesh)
    _, state, greport = guard.run_guarded(
        rt, 4, 6, guard.GuardConfig(check_every=2)
    )
    expected = _run_plain(geom, 4, 6)
    np.testing.assert_array_equal(np.asarray(state.board), expected)
    assert greport.failures == 0


def test_guarded_sharded_recovery():
    geom = Geometry(size=16, num_ranks=4)
    mesh = mesh_mod.make_mesh_1d()
    fired = []

    def fault_once(board, generation):
        if generation == 4 and not fired:
            fired.append(generation)
            return guard.inject_bitflip(board, 10, 3)
        return board

    rt = GolRuntime(geometry=geom, mesh=mesh)
    _, state, greport = guard.run_guarded(
        rt, 4, 8, guard.GuardConfig(check_every=4, fault_hook=fault_once)
    )
    np.testing.assert_array_equal(
        np.asarray(state.board), _run_plain(geom, 4, 8)
    )
    assert greport.restores == 1
    assert fired == [4]


def test_guard_config_validation():
    with pytest.raises(ValueError, match="check_every"):
        guard.GuardConfig(check_every=0)
    with pytest.raises(ValueError, match="max_restores"):
        guard.GuardConfig(check_every=1, max_restores=-1)


# -- snapshot integrity ------------------------------------------------------


def test_checkpoint_fingerprint_roundtrip(tmp_path):
    board = _rand_board(16, 8, seed=6)
    path = ckpt_mod.save(str(tmp_path / "a.gol.npz"), board, 12, 2)
    snap = ckpt_mod.load(path)
    np.testing.assert_array_equal(snap.board, board)
    assert snap.generation == 12


def test_tampered_checkpoint_rejected(tmp_path):
    board = _rand_board(16, 8, seed=7)
    path = ckpt_mod.save(str(tmp_path / "b.gol.npz"), board, 5, 1)
    # Tamper: rewrite with a flipped cell but the ORIGINAL fingerprint.
    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files}
    arrays["board"] = arrays["board"].copy()
    arrays["board"][0, 0] ^= 1
    np.savez_compressed(path, **arrays)
    with pytest.raises(ckpt_mod.CorruptSnapshotError, match="fingerprint"):
        ckpt_mod.load(path)


def test_tampered_halo_rejected(tmp_path):
    board = _rand_board(16, 8, seed=9)
    halo = _rand_board(2, 8, seed=10)
    path = ckpt_mod.save(
        str(tmp_path / "h.gol.npz"), board, 5, 1, top0=halo[0], bottom0=halo[1]
    )
    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files}
    arrays["top0"] = arrays["top0"].copy()
    arrays["top0"][0] ^= 1
    np.savez_compressed(path, **arrays)
    with pytest.raises(ckpt_mod.CorruptSnapshotError, match="halo"):
        ckpt_mod.load(path)


def test_legacy_checkpoint_without_fingerprint_loads(tmp_path):
    board = _rand_board(8, 8, seed=8)
    path = str(tmp_path / "legacy.gol.npz")
    np.savez_compressed(
        path,
        board=board,
        generation=np.int64(3),
        num_ranks=np.int64(1),
    )
    snap = ckpt_mod.load(path)
    assert snap.generation == 3


def test_guarded_run_writes_checkpoints(tmp_path):
    geom = Geometry(size=16, num_ranks=2)
    ckdir = str(tmp_path / "ck")
    rt = GolRuntime(geometry=geom, checkpoint_every=4, checkpoint_dir=ckdir)
    _, state, _ = guard.run_guarded(rt, 4, 10, guard.GuardConfig(check_every=3))
    # Audit boundaries are 3,6,9,10; the first >=4 is 6, then the next
    # interval target is 6+4=10 -> snapshots at generations 6 and 10.
    paths = [ckpt_mod.checkpoint_path(ckdir, g) for g in (6, 10)]
    for p in paths:
        snap = ckpt_mod.load(p)  # load verifies the fingerprint
        assert snap.num_ranks == 2
    # The last snapshot (generation 10) IS the final audited state, and a
    # resumed runtime accepts it.
    np.testing.assert_array_equal(
        ckpt_mod.load(paths[-1]).board, np.asarray(state.board)
    )
    rt2 = GolRuntime(geometry=geom)
    _, state2 = rt2.run(pattern=4, iterations=0, resume=paths[-1])
    assert int(state2.generation) == 10


# -- CLI surface -------------------------------------------------------------


def test_cli_guarded_run(tmp_path, capsys, monkeypatch):
    from gol_tpu import cli

    monkeypatch.chdir(tmp_path)
    rc = cli.main(["4", "16", "6", "64", "1", "--guard-every", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "TOTAL DURATION" in out
    assert "GUARD          : 3 checks, 0 failures, 0 restores" in out
    assert (tmp_path / "Rank_0_of_1.txt").exists()


def test_cli_rejects_negative_guard_every(capsys):
    from gol_tpu import cli

    rc = cli.main(["4", "16", "2", "64", "0", "--guard-every", "-5"])
    assert rc == 255
    assert "--guard-every" in capsys.readouterr().out


def test_cli_guard_rejects_profile(capsys):
    from gol_tpu import cli

    rc = cli.main(
        ["4", "16", "2", "64", "0", "--guard-every", "1", "--profile", "/tmp/x"]
    )
    assert rc == 255
    assert "unguarded" in capsys.readouterr().out


def test_guarded_flagship_sharded_pallas():
    """run_guarded over the fused-kernel-per-shard engine (interpret mode):
    audits, rollback bookkeeping, and the final board all line up."""
    geom = Geometry(size=32, num_ranks=4)  # 128x32, shard height 32
    rt = GolRuntime(
        geometry=geom,
        engine="pallas_bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
        halo_depth=8,
    )
    _, state, greport = guard.run_guarded(
        rt, 4, 16, guard.GuardConfig(check_every=8)
    )
    np.testing.assert_array_equal(
        np.asarray(state.board), _run_plain(geom, 4, 16)
    )
    assert greport.checks == 2 and greport.failures == 0


# -- cross-engine redundancy audit (VERDICT r1 #5) ---------------------------


def test_in_range_flip_provably_missed_without_redundant():
    """The documented blind spot, pinned: a flip to a VALID cell value
    passes the 0/1 invariant and the plain guard ships the corruption."""
    geom = Geometry(size=32, num_ranks=2)

    def flip_valid(board, generation):
        if generation == 6:
            return guard.inject_bitflip(board, 2, 2, value=1)  # in-range
        return board

    rt = GolRuntime(geometry=geom)
    _, state, greport = guard.run_guarded(
        rt, 4, 10, guard.GuardConfig(check_every=3, fault_hook=flip_valid)
    )
    assert greport.failures == 0  # nothing noticed...
    with pytest.raises(AssertionError):  # ...and the result is wrong
        np.testing.assert_array_equal(
            np.asarray(state.board), _run_plain(geom, 4, 10)
        )


def test_in_range_flip_caught_by_redundant_audit():
    """The same fault with --guard-redundant: the second engine's
    fingerprint disagrees, the guard rolls back and replays to the exact
    clean result."""
    geom = Geometry(size=32, num_ranks=2)
    fired = []

    def flip_valid_once(board, generation):
        if generation == 6 and not fired:
            fired.append(generation)
            return guard.inject_bitflip(board, 2, 2, value=1)
        return board

    rt = GolRuntime(geometry=geom)
    _, state, greport = guard.run_guarded(
        rt,
        4,
        10,
        guard.GuardConfig(
            check_every=3, fault_hook=flip_valid_once, redundant=True
        ),
    )
    np.testing.assert_array_equal(
        np.asarray(state.board), _run_plain(geom, 4, 10)
    )
    assert greport.failures == 1 and greport.restores == 1
    # Every audit carries the checker fingerprint; the good ones agree.
    assert all(a.redundant_fingerprint is not None for a in greport.audits)
    good = [a for a in greport.audits if a.ok]
    assert all(a.redundant_fingerprint == a.fingerprint for a in good)


def test_redundant_clean_run_matches_unguarded():
    geom = Geometry(size=32, num_ranks=2)
    rt = GolRuntime(geometry=geom, engine="bitpack")
    _, state, greport = guard.run_guarded(
        rt, 4, 8, guard.GuardConfig(check_every=4, redundant=True)
    )
    np.testing.assert_array_equal(
        np.asarray(state.board), _run_plain(geom, 4, 8)
    )
    assert greport.failures == 0


def test_redundant_persistent_fault_names_the_mismatch():
    geom = Geometry(size=32, num_ranks=2)

    def always_flip(board, generation):
        return guard.inject_bitflip(board, 1, 1, value=1)

    rt = GolRuntime(geometry=geom)
    with pytest.raises(guard.GuardError, match="redundant recompute"):
        guard.run_guarded(
            rt,
            4,
            4,
            guard.GuardConfig(
                check_every=2,
                max_restores=1,
                fault_hook=always_flip,
                redundant=True,
            ),
        )


def test_redundant_sharded_run():
    geom = Geometry(size=32, num_ranks=4)  # 128x32
    mesh = mesh_mod.make_mesh_1d(4)
    fired = []

    def flip_valid_once(board, generation):
        if generation == 4 and not fired:
            fired.append(generation)
            return guard.inject_bitflip(board, 40, 3, value=1)
        return board

    rt = GolRuntime(geometry=geom, mesh=mesh)
    _, state, greport = guard.run_guarded(
        rt,
        4,
        8,
        guard.GuardConfig(
            check_every=4, fault_hook=flip_valid_once, redundant=True
        ),
    )
    np.testing.assert_array_equal(
        np.asarray(state.board), _run_plain(geom, 4, 8)
    )
    assert greport.failures == 1 and greport.restores == 1


def test_checker_runtime_picks_a_different_engine():
    geom = Geometry(size=32, num_ranks=1)
    assert (
        guard._checker_runtime(GolRuntime(geometry=geom, engine="dense"))
        ._resolved == "bitpack"
    )
    assert (
        guard._checker_runtime(GolRuntime(geometry=geom, engine="bitpack"))
        ._resolved == "dense"
    )
    # A dense run whose width cannot pack has no second engine.
    with pytest.raises(ValueError, match="redundant audit"):
        guard._checker_runtime(
            GolRuntime(geometry=Geometry(size=20, num_ranks=1))
        )


def test_cli_guard_redundant_flag(tmp_path, capsys, monkeypatch):

    from gol_tpu import cli

    monkeypatch.chdir(tmp_path)
    rc = cli.main(
        ["4", "32", "6", "16", "0", "--guard-every", "3", "--guard-redundant"]
    )
    assert rc == 0
    assert "GUARD          : 2 checks, 0 failures, 0 restores" in (
        capsys.readouterr().out
    )
    # The flag without an audit cadence is meaningless.
    assert (
        cli.main(["4", "32", "6", "16", "0", "--guard-redundant"]) == 255
    )


def test_corrupt_rollback_base_fails_loud(monkeypatch):
    """A fault landing in the device-resident last-good buffer itself must
    abort recovery, not silently replay-and-certify the corruption."""
    geom = Geometry(size=32, num_ranks=2)
    real_copy = guard._device_copy
    calls = []

    def evil_copy(x):
        # Corrupt only the initial snapshot copy (an in-range flip, so
        # only the fingerprint comparison can see it); later copies are
        # faithful, so the restore reads the corrupted base as-is.
        out = real_copy(x)
        if not calls:
            calls.append(1)
            out = out.at[0, 0].set(1 - out[0, 0])
        return out

    monkeypatch.setattr(guard, "_device_copy", evil_copy)

    def fault_once(board, generation):
        if generation == 3:
            return guard.inject_bitflip(board, 2, 2)  # out-of-range: restore
        return board

    rt = GolRuntime(geometry=geom)
    with pytest.raises(guard.GuardError, match="rollback base"):
        guard.run_guarded(
            rt, 4, 6, guard.GuardConfig(check_every=3, fault_hook=fault_once)
        )

# -- redundancy-audit sampling (--guard-redundant-every, round 3) ------------


def test_sampled_redundant_catches_flip_in_sampled_chunk():
    """N=4 sampling: a one-shot in-range flip landing in a SAMPLED chunk
    is caught, rolled back, and replayed to the exact clean result.
    (Pattern 4 is a corner blinker, so cell (20,20) is 0 on every clean
    trajectory — the flip provably changes the board.)"""
    geom = Geometry(size=32, num_ranks=2)
    fired = []

    def flip_once(board, generation):
        if generation == 10 and not fired:  # audit ordinal 4: sampled
            fired.append(generation)
            return guard.inject_bitflip(board, 20, 20, value=1)
        return board

    rt = GolRuntime(geometry=geom)
    _, state, greport = guard.run_guarded(
        rt,
        4,
        16,
        guard.GuardConfig(
            check_every=2,
            fault_hook=flip_once,
            redundant=True,
            redundant_every=4,
        ),
    )
    assert greport.failures == 1 and greport.restores == 1
    first_fail = next(i for i, a in enumerate(greport.audits) if not a.ok)
    assert first_fail == 4  # the sampled ordinal
    # Only sampled audits paid the recompute: ordinals 0 and 4 (plus 4's
    # forced-redundant replay); the other audits are cheap.
    unsampled = [greport.audits[i] for i in (1, 2, 3)]
    assert all(a.ok and a.redundant_fingerprint is None for a in unsampled)
    assert greport.audits[0].redundant_fingerprint is not None
    np.testing.assert_array_equal(
        np.asarray(state.board), _run_plain(geom, 4, 16)
    )


def test_sampled_redundant_documents_missed_coverage():
    """The trade-off, pinned honestly: a one-shot flip in an UNSAMPLED
    chunk is never caught (it becomes the recompute baseline)."""
    geom = Geometry(size=32, num_ranks=2)
    fired = []

    def flip_once(board, generation):
        if generation == 4 and not fired:  # audit 1: unsampled at N=4
            fired.append(generation)
            return guard.inject_bitflip(board, 2, 2, value=1)
        return board

    rt = GolRuntime(geometry=geom)
    _, state, greport = guard.run_guarded(
        rt,
        4,
        16,
        guard.GuardConfig(
            check_every=2,
            fault_hook=flip_once,
            redundant=True,
            redundant_every=4,
        ),
    )
    assert greport.failures == 0  # missed by design
    with pytest.raises(AssertionError):
        np.testing.assert_array_equal(
            np.asarray(state.board), _run_plain(geom, 4, 16)
        )


def test_sampled_redundant_replay_stays_verified():
    """A persistent fault first caught at a sampled audit must keep
    failing its replays (force_redundant), exhausting the budget — never
    slip through on an unsampled cheap-audit replay."""
    geom = Geometry(size=32, num_ranks=2)

    def always_flip(board, generation):
        return guard.inject_bitflip(board, 1, 1, value=1)

    rt = GolRuntime(geometry=geom)
    with pytest.raises(guard.GuardError, match="redundant recompute"):
        guard.run_guarded(
            rt,
            4,
            8,
            guard.GuardConfig(
                check_every=2,
                max_restores=2,
                fault_hook=always_flip,
                redundant=True,
                redundant_every=4,
            ),
        )


def test_redundant_every_validation():
    with pytest.raises(ValueError, match="redundant_every"):
        guard.GuardConfig(check_every=1, redundant_every=0)


def test_cli_guard_redundant_every_flag(tmp_path, capsys, monkeypatch):
    from gol_tpu import cli

    monkeypatch.chdir(tmp_path)
    rc = cli.main(
        ["4", "32", "8", "16", "0", "--guard-every", "2",
         "--guard-redundant", "--guard-redundant-every", "2"]
    )
    assert rc == 0
    assert "GUARD          : 4 checks, 0 failures" in capsys.readouterr().out
    assert (
        cli.main(
            ["4", "32", "8", "16", "0", "--guard-every", "2",
             "--guard-redundant-every", "2"]
        )
        == 255
    )
