"""Sharded engines on the 8-device CPU mesh vs. the single-device result.

This is the test the reference never had (its halo logic shipped with bug
B1): the same program run 1-device and N-device must produce identical
boards.  Covers 1-D rings, 2-D blocks (edge + corner halos), the XLA
auto-SPMD mode, and degenerate meshes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.ops import stencil
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import sharded

from tests import oracle


def random_board(h, w, seed, density=0.35):
    return oracle.random_board(h, w, seed, density)


def devices():
    return jax.devices()


@pytest.mark.parametrize("num_devices", [1, 2, 4, 8])
@pytest.mark.parametrize("steps", [1, 2, 9])
def test_1d_ring_matches_single_device(num_devices, steps):
    board = random_board(16, 24, seed=num_devices * 100 + steps)
    mesh = mesh_mod.make_mesh_1d(num_devices)
    got = np.asarray(sharded.evolve_sharded(jnp.asarray(board), steps, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("shape", [(2, 4), (4, 2), (8, 1), (1, 8), (2, 2)])
def test_2d_blocks_match_single_device(shape):
    steps = 5
    board = random_board(16, 16, seed=sum(shape))
    mesh = mesh_mod.make_mesh_2d(shape, devices=devices()[: shape[0] * shape[1]])
    got = np.asarray(sharded.evolve_sharded(jnp.asarray(board), steps, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


def test_2d_corner_halo_crossing():
    """A glider aimed straight through a 2×2 shard corner: the corner cells
    must hop two mesh axes in one step (the two-phase exchange's whole
    point)."""
    board = np.zeros((16, 16), np.uint8)
    # Glider centered near the (8,8) corner junction, moving down-right.
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[6:9, 6:9] = g
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=devices()[:4])
    expected = oracle.run_torus(board, 12)
    got = np.asarray(sharded.evolve_sharded(jnp.asarray(board), 12, mesh))
    np.testing.assert_array_equal(got, expected)
    assert got.sum() == 5  # glider survived the corner crossing


@pytest.mark.parametrize("steps", [1, 6])
def test_auto_spmd_matches_single_device(steps):
    board = random_board(16, 16, seed=steps)
    mesh = mesh_mod.make_mesh_1d(4)
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), steps, mesh, mode="auto")
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("num_devices", [1, 2, 4, 8])
@pytest.mark.parametrize("steps", [1, 7])
def test_overlap_1d_matches_oracle(num_devices, steps):
    board = random_board(16, 24, seed=num_devices * 7 + steps)
    mesh = mesh_mod.make_mesh_1d(num_devices)
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), steps, mesh, mode="overlap")
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("shape", [(2, 4), (2, 2), (1, 8)])
def test_overlap_2d_matches_oracle(shape):
    board = random_board(16, 16, seed=sum(shape) * 3)
    mesh = mesh_mod.make_mesh_2d(shape, devices=devices()[: shape[0] * shape[1]])
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), 6, mesh, mode="overlap")
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 6))


def test_overlap_tiny_shards_fall_back():
    """Shards with h < 3 (1-D) or min(h, w) < 3 (2-D) are all boundary —
    the overlap split must degrade to the plain halo step, not miscompute."""
    board = random_board(16, 16, seed=5)
    mesh1 = mesh_mod.make_mesh_1d(8)  # h = 2 per shard
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), 4, mesh1, mode="overlap")
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 4))
    mesh2 = mesh_mod.make_mesh_2d((8, 1), devices=devices()[:8])
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), 4, mesh2, mode="overlap")
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 4))


def test_overlap_2d_glider_corner_crossing():
    board = np.zeros((16, 16), np.uint8)
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[6:9, 6:9] = g
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=devices()[:4])
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), 12, mesh, mode="overlap")
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 12))


@pytest.mark.parametrize("depth", [2, 3, 4])
@pytest.mark.parametrize("steps", [1, 4, 7, 9])
def test_deep_halo_1d_matches_oracle(depth, steps):
    """Temporal blocking: k-deep ghost bands, k local generations per
    exchange — including steps not divisible by k (remainder chunk)."""
    board = random_board(16, 24, seed=depth * 10 + steps)
    mesh = mesh_mod.make_mesh_1d(4)
    got = np.asarray(
        sharded.evolve_sharded(
            jnp.asarray(board), steps, mesh, halo_depth=depth
        )
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


@pytest.mark.parametrize("depth", [2, 3])
def test_deep_halo_2d_matches_oracle(depth):
    board = random_board(16, 16, seed=depth)
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=devices()[:4])
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), 7, mesh, halo_depth=depth)
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 7))


def test_deep_halo_glider_through_corner():
    board = np.zeros((16, 16), np.uint8)
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    board[6:9, 6:9] = g
    mesh = mesh_mod.make_mesh_2d((2, 2), devices=devices()[:4])
    got = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), 12, mesh, halo_depth=4)
    )
    np.testing.assert_array_equal(got, oracle.run_torus(board, 12))


def test_deep_halo_rejections():
    mesh = mesh_mod.make_mesh_1d(8)
    board = jnp.asarray(random_board(16, 16, seed=0))  # shard h = 2
    with pytest.raises(ValueError, match="halo depth"):
        sharded.evolve_sharded(board, 4, mesh, halo_depth=3)
    with pytest.raises(ValueError, match="explicit"):
        sharded.evolve_sharded(board, 4, mesh, mode="auto", halo_depth=2)
    with pytest.raises(ValueError, match=">= 1"):
        sharded.evolve_sharded(board, 4, mesh, halo_depth=0)


def test_single_row_shards():
    """h/R == 1: each shard owns exactly one row, so both its halo rows come
    from neighbors and its own row is simultaneously first and last."""
    board = random_board(8, 8, seed=3)
    mesh = mesh_mod.make_mesh_1d(8)
    got = np.asarray(sharded.evolve_sharded(jnp.asarray(board), 4, mesh))
    np.testing.assert_array_equal(got, oracle.run_torus(board, 4))


def test_pattern4_blinker_on_mesh():
    """The reference's de-facto probe (pattern 4) across a sharded wrap."""
    from gol_tpu.models import patterns

    board = patterns.init_global(4, 8, num_ranks=4)  # 32×8 world
    mesh = mesh_mod.make_mesh_1d(4)
    got2 = np.asarray(sharded.evolve_sharded(jnp.asarray(board), 2, mesh))
    np.testing.assert_array_equal(got2, board)  # period 2


def test_geometry_validation():
    mesh = mesh_mod.make_mesh_1d(8)
    with pytest.raises(ValueError, match="divisible"):
        sharded.evolve_sharded(jnp.zeros((12, 8), jnp.uint8), 1, mesh)
    with pytest.raises(ValueError, match="mode"):
        sharded.evolve_sharded(
            jnp.zeros((8, 8), jnp.uint8), 1, mesh, mode="bogus"
        )


def test_mesh_2d_auto_factorization():
    mesh = mesh_mod.make_mesh_2d()
    assert mesh.shape[mesh_mod.ROWS] * mesh.shape[mesh_mod.COLS] == len(devices())
    # 8 devices -> most square factorization is 2×4.
    assert mesh.shape[mesh_mod.ROWS] == 2 and mesh.shape[mesh_mod.COLS] == 4


def test_explicit_and_auto_agree_long_run():
    board = random_board(24, 24, seed=11)
    mesh1 = mesh_mod.make_mesh_1d(4)
    a = np.asarray(sharded.evolve_sharded(jnp.asarray(board), 20, mesh1))
    b = np.asarray(
        sharded.evolve_sharded(jnp.asarray(board), 20, mesh1, mode="auto")
    )
    c = np.asarray(stencil.run(jnp.asarray(board), 20))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)
