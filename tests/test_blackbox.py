"""Black-box flight recorder + crash forensics (docs/OBSERVABILITY.md).

What is pinned here:

- the ring is bounded by construction (``deque(maxlen)``): capacity
  honored, ``recorded_total``/``dropped`` accounting exact, capacity
  configurable via ``GOL_BLACKBOX_RING`` and killable via
  ``GOL_BLACKBOX=0``;
- a dump is a schema-valid v13 stream (header ``driver: "blackbox"``
  first, ring verbatim) that rotates ``.N`` like the EventLog rank
  file, and a dump from a FUTURE schema refuses with the standard
  exit-2 SchemaError instead of a KeyError;
- **trace identity**: recorder on vs. ``GOL_BLACKBOX=0`` traces
  byte-identical jaxprs — the ring is host-side by construction;
- the postmortem reconstruction (final chunks, open spans, journal
  cross-check, verdict) names the request a supervised replay would
  recover;
- ``GET /debug/blackbox`` streams the same bytes a crash dump would
  write, 404 when disabled;
- **red/green**: a real ``python -m gol_tpu.serve`` killed by an armed
  ``crash.exit`` mid-batch leaves a dump whose serve events agree with
  the journal fold (the postmortem verdict names the open request); a
  graceful SIGTERM drain of the same server leaves NO dump at all.
"""

from __future__ import annotations

import glob
import io
import json
import os
import pathlib
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from gol_tpu import telemetry
from gol_tpu.telemetry import blackbox
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Each test gets its own process-default ring."""
    blackbox.reset_for_tests()
    yield
    blackbox.reset_for_tests()


# -- the ring -----------------------------------------------------------------


def test_ring_is_bounded_with_exact_accounting():
    r = blackbox.FlightRecorder(capacity=4, run_id="ring")
    for i in range(10):
        r.record({"event": "serve", "t": float(i),
                  "action": "admit", "request_id": f"r{i}"})
    records, total = r.snapshot()
    assert total == 10
    assert [rec["request_id"] for rec in records] == [
        "r6", "r7", "r8", "r9"
    ]
    lines = r.dump_lines("unit")
    header = json.loads(lines[0])
    assert header["event"] == "run_header"
    assert header["config"] == {
        "driver": "blackbox", "reason": "unit", "capacity": 4,
        "recorded_total": 10, "dropped": 6, "pid": os.getpid(),
    }
    for ln in lines:
        telemetry.validate_record(json.loads(ln))


def test_ring_capacity_from_env(monkeypatch):
    monkeypatch.setenv(blackbox.ENV_RING, "7")
    assert blackbox.FlightRecorder().capacity == 7


def test_disable_knob_kills_the_recorder(monkeypatch, tmp_path):
    monkeypatch.setenv(blackbox.ENV_DISABLE, "0")
    blackbox.reset_for_tests()
    assert blackbox.recorder() is None
    blackbox.record_event("serve", action="admit", request_id="r1")
    assert blackbox.dump_now("unit") is None
    assert blackbox.install(str(tmp_path)) is None
    assert glob.glob(str(tmp_path / "*.blackbox.jsonl")) == []


def test_record_event_rings_without_an_eventlog():
    """The fallback tap: emission sites with no file sink still ring
    (the bare scheduler's serve/chunk records)."""
    blackbox.record_event("serve", action="admit", request_id="bare")
    records, total = blackbox.recorder().snapshot()
    assert total == 1
    assert records[0]["event"] == "serve"
    assert records[0]["request_id"] == "bare"
    assert isinstance(records[0]["t"], float)


def test_eventlog_emit_taps_the_default_ring(tmp_path):
    """Every record the v13 stream carries also lands in the ring —
    same dict, no re-validation cost on the hot path."""
    with telemetry.EventLog(
        str(tmp_path), run_id="tap", process_index=0
    ) as ev:
        ev.run_header({"driver": "test"})
        ev.chunk_event(0, 4, 4, 0.1, 1e6, None)
    file_recs = [json.loads(ln) for ln in open(ev.path)]
    ring, total = blackbox.recorder().snapshot()
    assert total == len(file_recs) == 2
    assert [r["event"] for r in ring] == ["run_header", "chunk"]


def test_dump_rotates_and_validates(tmp_path):
    r = blackbox.FlightRecorder(capacity=8, run_id="rot")
    r.configure(dump_dir=str(tmp_path))
    r.record({"event": "serve", "t": 1.0,
              "action": "admit", "request_id": "r1"})
    first = r.dump("one")
    second = r.dump("two")
    assert first == second == str(tmp_path / "rot.blackbox.jsonl")
    assert (tmp_path / "rot.blackbox.jsonl.1").exists()
    recs = blackbox.load_dump(second)
    assert recs[0]["config"]["reason"] == "two"
    rotated = blackbox.load_dump(str(tmp_path / "rot.blackbox.jsonl.1"))
    assert rotated[0]["config"]["reason"] == "one"


def test_dump_without_directory_is_a_noop():
    r = blackbox.FlightRecorder(capacity=2, run_id="homeless")
    assert r.dump("unit") is None


# -- trace identity -----------------------------------------------------------


def test_recorder_knob_never_changes_the_traced_program(monkeypatch):
    """Recorder on vs. GOL_BLACKBOX=0 traces byte-identical jaxprs —
    the ring runs strictly host-side, after the force_ready fences."""
    from gol_tpu.analysis import walker
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    for engine in ("dense", "bitpack"):
        jaxprs = {}
        for knob in ("1", "0"):
            monkeypatch.setenv(blackbox.ENV_DISABLE, knob)
            blackbox.reset_for_tests()
            rt = GolRuntime(
                geometry=Geometry(size=64, num_ranks=1), engine=engine
            )
            spec = jax.ShapeDtypeStruct((64, 64), np.uint8)
            fn, dynamic, static = rt._evolve_fn(4)
            jaxprs[knob] = str(
                walker.trace_jaxpr(fn, spec, *dynamic, *static)
            )
        assert jaxprs["1"] == jaxprs["0"], f"engine {engine} diverged"


# -- postmortem ---------------------------------------------------------------


def _synthetic_death(state: pathlib.Path) -> None:
    """A hand-built crash scene: r8 completed, r9 admitted+started in
    the journal with its trace still open in the ring."""
    state.mkdir(parents=True, exist_ok=True)
    (state / "journal.jsonl").write_text(
        "\n".join(
            json.dumps(rec)
            for rec in [
                {"rec": "admit", "id": "r8", "t": 0.5},
                {"rec": "start", "id": "r8", "t": 0.6},
                {"rec": "complete", "id": "r8", "t": 0.9},
                {"rec": "admit", "id": "r9", "t": 1.0},
                {"rec": "start", "id": "r9", "t": 1.1},
            ]
        )
        + "\n"
    )
    r = blackbox.FlightRecorder(capacity=64, run_id="synth")
    for rec in [
        {"event": "serve", "t": 1.0, "action": "admit",
         "request_id": "r9"},
        {"event": "serve", "t": 1.1, "action": "start",
         "request_id": "r9"},
        {"event": "span", "t": 1.2, "trace_id": "t-r9",
         "request_id": "r9", "span_id": "s1", "name": "queue",
         "start_t": 1.0, "end_t": 1.1},
        {"event": "chunk", "t": 1.3, "index": 0, "take": 4,
         "generation": 4, "wall_s": 0.01, "updates_per_sec": 1e6,
         "roofline_util": None},
        {"event": "guard_audit", "t": 1.35, "generation": 4, "ok": True,
         "max_cell": 1, "population": 12, "fingerprint": "abcd"},
        {"event": "chunk", "t": 1.4, "index": 1, "take": 4,
         "generation": 8, "wall_s": 0.01, "updates_per_sec": 1e6,
         "roofline_util": None},
    ]:
        r.record(rec)
    assert r.dump("exception:ValueError", str(state / "telemetry"))


def test_postmortem_reconstructs_the_last_seconds(tmp_path):
    state = tmp_path / "state"
    _synthetic_death(state)
    out = io.StringIO()
    assert blackbox.render_postmortem(str(state), out) == 0
    text = out.getvalue()
    assert "reason exception:ValueError" in text
    assert "chunk   1 (take 4) -> generation 8" in text
    assert "t-r9 (request r9): queue — no root span committed" in text
    assert "generation 4: ok, population 12" in text
    assert "2 request(s), 1 open intent(s)" in text
    assert (
        "r9: journal started, last recorded serve event 'start'" in text
    )
    assert (
        "request(s) r9 left open in the journal — a supervised replay "
        "will re-admit and complete it exactly once." in text
    )


def test_postmortem_without_a_dump_exits_1(tmp_path, capsys):
    assert summ_mod.main(["postmortem", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "no *.blackbox.jsonl dump under" in out
    assert "graceful drain leaves no dump" in out


def test_future_schema_dump_refuses_exit_2(tmp_path, capsys):
    future = telemetry.SCHEMA_VERSION + 1
    (tmp_path / "fut.blackbox.jsonl").write_text(
        json.dumps(
            {
                "event": "run_header", "t": 0.0, "schema": future,
                "run_id": "fut", "process_index": 0, "process_count": 1,
                "config": {"driver": "blackbox", "reason": "unit"},
            }
        )
        + "\n"
    )
    assert summ_mod.main(["postmortem", str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert f"schema v{future} is newer than this reader supports" in err


def test_summarize_skips_dumps(tmp_path, capsys):
    """A state dir holding both a rank stream and a crash dump still
    summarizes — the dump is forensic, not a rank file."""
    with telemetry.EventLog(
        str(tmp_path), run_id="both", process_index=0
    ) as ev:
        ev.run_header({"driver": "test"})
    blackbox.install(str(tmp_path), run_id="both")
    blackbox.dump_now("unit")
    assert (tmp_path / "both.blackbox.jsonl").exists()
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    assert "both" in capsys.readouterr().out


# -- /debug/blackbox ----------------------------------------------------------


def test_debug_blackbox_endpoint_streams_the_ring(tmp_path):
    from gol_tpu.serve.scheduler import ServeScheduler
    from gol_tpu.serve.server import ServeServer

    sched = ServeScheduler(
        str(tmp_path / "state"), quantum=32, slots=2, chunk=2
    )
    srv = ServeServer(sched, 0)
    try:
        sched.submit(
            {"id": "dbg", "pattern": 4, "size": 32, "generations": 4}
        )
        sched.run_until_drained()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/blackbox", timeout=30
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/x-ndjson"
            lines = resp.read().decode().splitlines()
    finally:
        srv.close()
        sched.close()
    recs = [json.loads(ln) for ln in lines if ln]
    for rec in recs:
        telemetry.validate_record(rec)
    assert recs[0]["event"] == "run_header"
    assert recs[0]["config"]["driver"] == "blackbox"
    assert recs[0]["config"]["reason"] == "debug.endpoint"
    # The bare scheduler has no EventLog, yet the ring saw the run.
    events = {r["event"] for r in recs}
    assert {"serve", "chunk"} <= events


def test_debug_blackbox_404_when_disabled(tmp_path):
    from gol_tpu.serve.scheduler import ServeScheduler
    from gol_tpu.serve.server import ServeServer

    sched = ServeScheduler(str(tmp_path / "state"), quantum=32)
    srv = ServeServer(sched, 0)
    blackbox._default = False  # as if GOL_BLACKBOX=0 at first use
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/blackbox", timeout=30
            )
        assert e.value.code == 404
    finally:
        srv.close()
        sched.close()


# -- red/green: a real server -------------------------------------------------


def _serve_env() -> dict:
    env = {
        **os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO),
    }
    for k in ("XLA_FLAGS", "GOL_FAULT_PLAN", "GOL_RESTART_ATTEMPT",
              "GOL_BLACKBOX", "GOL_BLACKBOX_RING"):
        env.pop(k, None)
    return env


def _serve_cmd(state: str) -> list:
    return [
        sys.executable, "-m", "gol_tpu.serve",
        "--state-dir", state, "--port", "0",
        "--run-id", "bb", "--chunk", "4", "--slots", "2",
    ]


def _read_port(proc) -> int:
    """The server prints its ephemeral port on the first line."""
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "listening on" in line:
            return int(line.split(":")[-1].split()[0])
        if proc.poll() is not None:
            break
        time.sleep(0.01)
    raise AssertionError("server never announced its port")


def test_crash_exit_dump_agrees_with_journal(tmp_path):
    """RED: crash.exit armed mid-batch kills the process between chunks;
    the black box dumps through the crash hook and the postmortem
    verdict names the request a supervised replay would recover."""
    from gol_tpu.serve import journal as journal_mod
    from gol_tpu.serve.client import SimClient

    state = str(tmp_path / "state")
    env = _serve_env()
    env["GOL_FAULT_PLAN"] = json.dumps(
        {"faults": [{"site": "crash.exit", "at": 4, "value": 23}]}
    )
    proc = subprocess.Popen(
        _serve_cmd(state), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        port = _read_port(proc)
        client = SimClient(f"http://127.0.0.1:{port}")
        try:
            client.submit(
                {"id": "r1", "pattern": 4, "size": 32, "generations": 16},
                connect_retries=20, retry_delay_s=0.5,
            )
        except (urllib.error.URLError, ConnectionError, OSError):
            # The crash can race the 202: the admit is journaled (and
            # rung) before the run loop reaches generation 4, but
            # os._exit kills the handler thread mid-response.  The
            # journal + dump assertions below are the real contract.
            pass
        assert proc.wait(timeout=180) == 23  # the armed exit code
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    dumps = blackbox.find_dumps(state)
    assert len(dumps) == 1 and dumps[0].endswith("bb.blackbox.jsonl")
    recs = blackbox.load_dump(dumps[0])
    assert recs[0]["config"]["driver"] == "blackbox"
    assert recs[0]["config"]["reason"].startswith("crash.exit:gen")
    # The ring's serve events agree with the journal fold: r1 is open
    # in BOTH planes — admitted/started, never completed.
    serve_ids = {
        r["request_id"] for r in recs if r["event"] == "serve"
    }
    assert "r1" in serve_ids
    assert not any(
        r["event"] == "serve" and r["action"] == "complete"
        for r in recs
    )
    entries, _ = journal_mod.replay(os.path.join(state, "journal.jsonl"))
    assert entries["r1"]["status"] in ("admitted", "started")

    out = io.StringIO()
    assert blackbox.render_postmortem(state, out) == 0
    text = out.getvalue()
    assert "request(s) r1 left open in the journal" in text
    assert "a supervised replay will re-admit and complete it" in text


def test_sigterm_drain_leaves_no_dump(tmp_path):
    """GREEN: a graceful SIGTERM drain finishes the committed request,
    exits 0, and leaves NO *.blackbox.jsonl anywhere — the graceful
    handler owns SIGTERM, the recorder only observes deaths."""
    from gol_tpu.serve.client import SimClient

    state = str(tmp_path / "state")
    proc = subprocess.Popen(
        _serve_cmd(state), env=_serve_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        port = _read_port(proc)
        client = SimClient(f"http://127.0.0.1:{port}")
        client.submit(
            {"id": "d1", "pattern": 4, "size": 32, "generations": 40},
            connect_retries=20, retry_delay_s=0.5,
        )
        proc.send_signal(signal.SIGTERM)  # mid-flight drain
        assert proc.wait(timeout=180) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    assert glob.glob(
        os.path.join(state, "**", "*.blackbox.jsonl"), recursive=True
    ) == []
    # The drain completed the committed request before exiting.
    result = json.load(open(os.path.join(state, "results", "d1.json")))
    assert result["status"] == "done"
    assert summ_mod.main(["postmortem", state]) == 1
