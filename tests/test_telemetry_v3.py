"""Schema v3 (resilience events), v1/v2 back-compat, restart storms.

Companion to tests/test_telemetry.py (v1-era pins) and
tests/test_telemetry_v2.py (v2 pins).  Here:

- the v3 additions round-trip: ``preempt``/``resume``/``restart``;
- **back-compat**: BOTH committed fixtures — the PR 2 (schema v1) and
  PR 3 (schema v2) streams — still load, and a directory holding v1 +
  v2 + a freshly-written v3 stream merges and renders in one
  ``summarize`` pass (exit 0), while a bogus schema still exits 2;
- the restart-storm watchdog flags > N ``restart`` events per window
  across a directory's runs (each supervised attempt is its own run)
  and stays quiet for slow restarts;
- ``summarize`` renders supervisor manifests next to the event streams
  (the join the run-manifest exists for), and the resume-fallback
  anomaly fires;
- ``watch`` shows the supervised/resumed/preempted status lines.
"""

from __future__ import annotations

import io
import json
import pathlib
import shutil

import pytest

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod
from gol_tpu.telemetry import watch as watch_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
V1_FIXTURE = DATA / "telemetry_v1" / "pr2run.rank0.jsonl"
V2_FIXTURE = DATA / "telemetry_v2" / "pr3run.rank0.jsonl"


# -- v3 round-trip -----------------------------------------------------------


def test_resilience_events_roundtrip(tmp_path):
    with telemetry.EventLog(str(tmp_path), run_id="v3", process_index=0) as ev:
        ev.run_header({"driver": "2d"})
        ev.restart_event(2)
        ev.resume_event(
            generation=8, path="/ck/ckpt_000000000008.gol.npz",
            fallback=True, skipped=["ckpt_000000000010.gol.npz"],
        )
        ev.preempt_event(12, checkpointed=True)
        path = ev.path
    recs = [json.loads(ln) for ln in open(path)]
    assert [r["event"] for r in recs] == [
        "run_header", "restart", "resume", "preempt"
    ]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 3
    assert recs[1]["attempt"] == 2
    assert recs[2]["fallback"] is True
    assert recs[2]["skipped"] == ["ckpt_000000000010.gol.npz"]
    assert recs[3] == {**recs[3], "generation": 12, "checkpointed": True}
    for r in recs:
        telemetry.validate_record(r)  # must not raise


@pytest.mark.parametrize(
    "rec",
    [
        {"event": "preempt", "t": 1.0, "generation": 4},  # no checkpointed
        {"event": "resume", "t": 1.0, "generation": 4, "path": "x"},
        {"event": "restart", "t": 1.0},
    ],
)
def test_validate_rejects_incomplete_v3_records(rec):
    with pytest.raises(telemetry.SchemaError):
        telemetry.validate_record(rec)


# -- back-compat: v1 + v2 fixtures + fresh v3 in one directory ---------------


def test_v1_v2_v3_merge_in_one_pass(tmp_path):
    shutil.copy(V1_FIXTURE, tmp_path / V1_FIXTURE.name)
    shutil.copy(V2_FIXTURE, tmp_path / V2_FIXTURE.name)
    with telemetry.EventLog(str(tmp_path), run_id="now", process_index=0) as ev:
        ev.run_header({"driver": "2d"})
        ev.resume_event(generation=4, path="/ck/x", fallback=False)
    out = io.StringIO()
    assert summ_mod.summarize(str(tmp_path), out) == 0
    text = out.getvalue()
    assert "run pr2run" in text and "run pr3run" in text
    assert "run now" in text
    assert "resume: generation 4" in text


def test_committed_fixture_schemas_are_v1_and_v2():
    v1 = json.loads(V1_FIXTURE.open().readline())
    v2 = json.loads(V2_FIXTURE.open().readline())
    assert v1["schema"] == 1 and v2["schema"] == 2
    assert set(telemetry.SUPPORTED_SCHEMAS) >= {1, 2, 3}


def test_unknown_schema_still_exits_2(tmp_path):
    rec = {
        "event": "run_header", "t": 1.0, "schema": 99, "run_id": "x",
        "process_index": 0, "process_count": 1, "config": {},
    }
    (tmp_path / "x.rank0.jsonl").write_text(json.dumps(rec) + "\n")
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2


# -- restart-storm watchdog --------------------------------------------------


def _runs_with_restarts(times):
    runs = {}
    for i, t in enumerate(times):
        run = summ_mod.Run(f"a{i}")
        run.ranks[0] = [{"event": "restart", "t": t, "attempt": i + 1}]
        runs[run.run_id] = run
    return runs


def test_restart_storm_flagged():
    flags = summ_mod.restart_storm_flags(
        _runs_with_restarts([0.0, 10.0, 20.0, 30.0]),
        max_restarts=3,
        window_s=300.0,
    )
    assert len(flags) == 1 and "restart storm" in flags[0]


def test_slow_restarts_not_flagged():
    flags = summ_mod.restart_storm_flags(
        _runs_with_restarts([0.0, 400.0, 800.0, 1200.0]),
        max_restarts=3,
        window_s=300.0,
    )
    assert flags == []


def test_storm_rendered_by_summarize_and_watch(tmp_path):
    for i in range(5):
        with telemetry.EventLog(
            str(tmp_path), run_id=f"a{i}", process_index=0
        ) as ev:
            ev.run_header({"driver": "2d"})
            if i:
                ev.restart_event(i)
    out = io.StringIO()
    assert summ_mod.summarize(str(tmp_path), out) == 0
    assert "ANOMALY: restart storm" in out.getvalue()
    out = io.StringIO()
    assert watch_mod.watch(str(tmp_path), out, frames=1, interval=0) == 0
    assert "ANOMALY: restart storm" in out.getvalue()


# -- resume-fallback anomaly + manifest rendering ----------------------------


def test_resume_fallback_anomaly_flagged(tmp_path):
    with telemetry.EventLog(str(tmp_path), run_id="fb", process_index=0) as ev:
        ev.run_header({"driver": "2d"})
        ev.resume_event(
            generation=8, path="/ck/x", fallback=True,
            skipped=["ckpt_000000000010.gol.npz"],
        )
    out = io.StringIO()
    assert summ_mod.summarize(str(tmp_path), out) == 0
    text = out.getvalue()
    assert "ANOMALY: resume fallback" in text
    assert "ckpt_000000000010.gol.npz" in text


def test_summarize_renders_supervisor_manifest(tmp_path):
    with telemetry.EventLog(str(tmp_path), run_id="j", process_index=0) as ev:
        ev.run_header({"driver": "2d"})
    manifest = dict(
        run_id="j",
        child=["python", "-m", "gol_tpu"],
        max_restarts=3,
        checkpoint_dir="ck",
        attempts=[
            dict(attempt=0, pid=11, exit_code=75, resume_generation=None),
            dict(attempt=1, pid=12, exit_code=0, resume_generation=6),
        ],
        finished=True,
        final_exit=0,
    )
    (tmp_path / "j.manifest.json").write_text(json.dumps(manifest))
    out = io.StringIO()
    assert summ_mod.summarize(str(tmp_path), out) == 0
    text = out.getvalue()
    assert "supervisor manifest j.manifest.json (run j)" in text
    assert "attempt 0: preempted, resumed from fresh start" in text
    assert "attempt 1: ok, resumed from generation 6" in text


def test_watch_renders_resilience_status(tmp_path):
    with telemetry.EventLog(str(tmp_path), run_id="w", process_index=0) as ev:
        ev.run_header({"driver": "2d"})
        ev.restart_event(1)
        ev.resume_event(generation=4, path="/ck/x", fallback=True)
        ev.preempt_event(8, checkpointed=True)
    out = io.StringIO()
    assert watch_mod.watch(str(tmp_path), out, frames=1, interval=0) == 0
    text = out.getvalue()
    assert "supervised: attempt 1" in text
    assert "resumed from generation 4  [FALLBACK]" in text
    assert "PREEMPTED at generation 8 (checkpointed)" in text
