"""Schema v8 (halo-exchange chunk block) + v1–v7 back-compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..7}.py.
Here:

- the v8 addition round-trips: sharded ring-engine chunks carry a
  ``halo`` block — the exchange depth/mode the chunk program compiled,
  the per-chunk exchange count, and the band traffic with its payload
  share (docs/OBSERVABILITY.md);
- a REAL pipelined runtime run emits the block on every chunk, with the
  accounting matching the chunk schedule (exactly ⌈take/k⌉ exchanges);
- **back-compat**: ALL SEVEN committed fixtures — PR 2 (v1) through
  PR 9 (v7) — still load, and a directory holding v1–v7 + a fresh v8
  stream merges and renders in one ``summarize`` pass (exit 0)
  including the halo column, while a bogus schema still exits 2.
"""

from __future__ import annotations

import json
import math
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
}

HALO_BLOCK = {
    "depth": 4,
    "mode": "pipeline",
    "exchanges": 2,
    "band_bytes": 2048,
    "exchange_share": 0.015,
}


def _v8_stream(directory, run_id="v8"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "2d", "engine": "bitpack",
             "resolved_engine": "bitpack", "shard_mode": "pipeline",
             "halo_depth": 4, "height": 64, "width": 64,
             "mesh": {"rows": 4}}
        )
        ev.compile_event(8, 0.01, 0.09)
        ev.chunk_event(0, 8, 8, 0.002, 32768, None, halo=HALO_BLOCK)
        return ev.path


def test_v8_halo_block_roundtrip(tmp_path):
    path = _v8_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 8
    assert set(telemetry.SUPPORTED_SCHEMAS) >= {1, 2, 3, 4, 5, 6, 7, 8}
    chunk = recs[2]
    assert chunk["event"] == "chunk"
    assert chunk["halo"]["mode"] == "pipeline"
    assert chunk["halo"]["depth"] == 4
    assert chunk["halo"]["exchanges"] == 2


def test_real_pipelined_run_stamps_halo_blocks(tmp_path):
    """End to end through GolRuntime: every chunk of a pipelined sharded
    run carries the v8 block, and the accounting matches the schedule."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="bitpack",
        mesh=mesh_mod.make_mesh_1d(4),
        shard_mode="pipeline",
        halo_depth=4,
        telemetry_dir=str(tmp_path),
        run_id="halorun",
    )
    rt.run(pattern=5, iterations=10)
    recs = [
        json.loads(ln)
        for ln in open(tmp_path / "halorun.rank0.jsonl")
    ]
    chunks = [r for r in recs if r["event"] == "chunk"]
    assert chunks
    for c in chunks:
        hb = c["halo"]
        assert hb["mode"] == "pipeline" and hb["depth"] == 4
        assert hb["exchanges"] == math.ceil(c["take"] / 4)
        assert hb["band_bytes"] > 0
        assert 0.0 < hb["exchange_share"] < 1.0


def test_explicit_depth1_run_still_stamps_contract(tmp_path):
    """The block is mode-agnostic ring accounting: explicit depth-1 runs
    report one exchange per generation."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        mesh=mesh_mod.make_mesh_1d(4),
        telemetry_dir=str(tmp_path),
        run_id="exprun",
    )
    rt.run(pattern=5, iterations=6)
    chunks = [
        json.loads(ln)
        for ln in open(tmp_path / "exprun.rank0.jsonl")
        if '"chunk"' in ln
    ]
    chunks = [c for c in chunks if c["event"] == "chunk"]
    assert chunks
    for c in chunks:
        assert c["halo"]["depth"] == 1
        assert c["halo"]["mode"] == "explicit"
        assert c["halo"]["exchanges"] == c["take"]


def test_unsharded_run_has_no_halo_block(tmp_path):
    """mesh none: no ring, no block — the stream stays v1-shaped there
    (and the PR 2 trace-identity pin keeps holding)."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="bitpack",
        telemetry_dir=str(tmp_path),
        run_id="solo",
    )
    rt.run(pattern=5, iterations=6)
    chunks = [
        json.loads(ln)
        for ln in open(tmp_path / "solo.rank0.jsonl")
        if '"chunk"' in ln
    ]
    assert all("halo" not in c for c in chunks if c["event"] == "chunk")


def test_committed_fixture_schemas_are_v1_to_v7():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v7_fixture_carries_reshard():
    events = [json.loads(ln)["event"] for ln in FIXTURES[7].open()]
    assert "reshard" in events


def test_v1_to_v8_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v8_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "v8",
    ):
        assert run_id in out
    assert "halo (mode k exch band)" in out
    assert "pipeline k=4" in out


def test_bogus_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": 99, "run_id": "bad",
             "process_index": 0, "process_count": 1, "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
