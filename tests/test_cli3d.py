"""3-D driver surface: engines agree, dumps load, validation fires."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from gol_tpu import cli3d

jax.config.update("jax_platforms", "cpu")


def test_parse_rule3d():
    r = cli3d.parse_rule3d("bays4555")
    assert r.birth == frozenset({5}) and r.survive == frozenset({4, 5})
    r = cli3d.parse_rule3d("B5,6/S4,5,26")
    assert r.birth == frozenset({5, 6})
    assert r.survive == frozenset({4, 5, 26})
    with pytest.raises(ValueError, match="malformed"):
        cli3d.parse_rule3d("5/45")
    with pytest.raises(ValueError, match="> 26"):
        cli3d.parse_rule3d("B27/S")


@pytest.mark.parametrize("engine", ["dense", "bitpack"])
def test_engines_agree_on_dump(tmp_path, engine, capsys):
    rc = cli3d.main(
        ["2", "32", "3", "64", "1", "--engine", engine, "--outdir",
         str(tmp_path / engine)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "TOTAL DURATION" in out and "POPULATION" in out


def test_engine_dumps_are_identical(tmp_path):
    for engine in ("dense", "bitpack"):
        assert (
            cli3d.main(
                ["2", "32", "3", "64", "1", "--engine", engine, "--outdir",
                 str(tmp_path / engine)]
            )
            == 0
        )
    a = np.load(tmp_path / "dense" / "World3D_of_1.npy")
    b = np.load(tmp_path / "bitpack" / "World3D_of_1.npy")
    np.testing.assert_array_equal(a, b)


def test_sharded_3d_cli_matches_single(tmp_path):
    assert (
        cli3d.main(
            ["2", "32", "2", "64", "1", "--mesh", "3d", "--outdir",
             str(tmp_path / "mesh")]
        )
        == 0
    )
    assert (
        cli3d.main(
            ["2", "32", "2", "64", "1", "--engine", "dense", "--outdir",
             str(tmp_path / "single")]
        )
        == 0
    )
    np.testing.assert_array_equal(
        np.load(tmp_path / "mesh" / "World3D_of_1.npy"),
        np.load(tmp_path / "single" / "World3D_of_1.npy"),
    )


def test_validation(capsys):
    assert cli3d.main(["9", "16", "1", "64", "0"]) == 255
    assert "not been implemented" in capsys.readouterr().out
    assert cli3d.main(["2", "16", "1", "64", "0", "--rule", "wat"]) == 255
    assert cli3d.main(["2", "16", "1", "0", "0"]) == 255
    assert cli3d.main(["2", "16"]) == 255  # wrong arg count -> usage


def test_zero_iterations(tmp_path, capsys):
    rc = cli3d.main(
        ["1", "16", "0", "64", "1", "--outdir", str(tmp_path)]
    )
    assert rc == 0
    vol = np.load(tmp_path / "World3D_of_1.npy")
    assert vol.sum() == 16**3


def test_sharded_3d_custom_rule(tmp_path):
    """--mesh 3d + a custom rule through the packed sharded path.

    Size 64 over the 2x2x2 mesh gives x-shards 32 cells wide — exactly
    one packed word — so auto takes compiled_evolve3d_packed (size 32
    would silently fall back to the dense sharded engine)."""
    a = cli3d.main(
        ["2", "64", "2", "64", "1", "--mesh", "3d", "--rule", "B5,6/S4,5",
         "--outdir", str(tmp_path / "mesh")]
    )
    b = cli3d.main(
        ["2", "64", "2", "64", "1", "--engine", "dense", "--rule",
         "B5,6/S4,5", "--outdir", str(tmp_path / "single")]
    )
    assert a == 0 and b == 0
    np.testing.assert_array_equal(
        np.load(tmp_path / "mesh" / "World3D_of_1.npy"),
        np.load(tmp_path / "single" / "World3D_of_1.npy"),
    )


# -- checkpoint / resume (capability parity with the 2-D driver) -------------


def test_cli3d_checkpoint_and_resume_equivalence(tmp_path, capsys):
    """10 straight generations == 4 generations + snapshot + resumed 6."""
    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    out_a = tmp_path / "a"
    out_b = tmp_path / "b"
    ck = tmp_path / "ck"
    assert cli3d.main(
        ["2", "32", "10", "64", "1", "--outdir", str(out_a)]
    ) == 0
    assert cli3d.main(
        ["2", "32", "4", "64", "0", "--checkpoint-every", "4",
         "--checkpoint-dir", str(ck)]
    ) == 0
    resume = ckpt_mod.checkpoint3d_path(str(ck), 4)
    assert cli3d.main(
        ["2", "32", "6", "64", "1", "--resume", resume,
         "--outdir", str(out_b)]
    ) == 0
    import numpy as np_

    a = np_.load(out_a / "World3D_of_1.npy")
    b = np_.load(out_b / "World3D_of_1.npy")
    np_.testing.assert_array_equal(a, b)


def test_cli3d_resume_rule_mismatch_rejected(tmp_path, capsys):
    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    ck = tmp_path / "ck"
    assert cli3d.main(
        ["2", "32", "4", "64", "0", "--checkpoint-every", "4",
         "--checkpoint-dir", str(ck), "--rule", "bays5766"]
    ) == 0
    capsys.readouterr()
    rc = cli3d.main(
        ["2", "32", "2", "64", "0",
         "--resume", ckpt_mod.checkpoint3d_path(str(ck), 4)]
    )
    assert rc == 255
    assert "pass the matching --rule" in capsys.readouterr().out


def test_cli3d_resume_corrupt_snapshot_rejected(tmp_path, capsys):
    import numpy as np_

    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    path = ckpt_mod.checkpoint3d_path(str(tmp_path), 3)
    vol = np_.random.default_rng(0).integers(0, 2, (32, 32, 32), np_.uint8)
    ckpt_mod.save3d(path, vol, 3, "B5/S4,5")
    with np_.load(path) as data:
        arrays = {k: data[k].copy() for k in data.files}
    arrays["volume"][0, 0, 0] ^= 1  # in-range flip
    np_.savez_compressed(path, **arrays)
    capsys.readouterr()
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", path])
    assert rc == 255
    assert "corrupt" in capsys.readouterr().out


def test_cli3d_resume_missing_file_fails_clean(tmp_path, capsys):
    from gol_tpu import cli3d

    rc = cli3d.main(
        ["2", "32", "2", "64", "0", "--resume", str(tmp_path / "nope.npz")]
    )
    assert rc == 255  # OSError path: clean message, no traceback


def test_cli3d_resume_2d_checkpoint_rejected(tmp_path, capsys):
    import numpy as np_

    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    path = ckpt_mod.checkpoint_path(str(tmp_path), 1)
    ckpt_mod.save(path, np_.zeros((8, 8), np_.uint8), 1, num_ranks=1)
    capsys.readouterr()
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", path])
    assert rc == 255
    assert "not a 3-D snapshot" in capsys.readouterr().out


def test_cli3d_resume_truncated_snapshot_fails_clean(tmp_path, capsys):
    from gol_tpu import cli3d

    bad = tmp_path / "trunc.gol3d.npz"
    bad.write_bytes(b"PK\x03\x04 definitely not a real zip")
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", str(bad)])
    assert rc == 255
    assert "not a readable snapshot" in capsys.readouterr().out


def test_cli3d_resume_missing_fingerprint_fails_clean(tmp_path, capsys):
    import numpy as np_

    from gol_tpu import cli3d

    bad = tmp_path / "nofp.gol3d.npz"
    np_.savez_compressed(
        bad, volume=np_.zeros((32, 32, 32), np_.uint8)
    )
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", str(bad)])
    assert rc == 255
    assert "missing" in capsys.readouterr().out

def test_mesh_pallas_engine_matches_single_device(tmp_path, capsys):
    """--engine pallas --mesh 3d (H-unsharded shape): the fused sharded
    kernel per shard, byte-compared against the single-device dump."""
    rc = cli3d.main(
        ["2", "128", "10", "64", "1", "--mesh", "3d", "--mesh-shape",
         "2,1,4", "--engine", "pallas", "--outdir", str(tmp_path / "a")]
    )
    assert rc == 0, capsys.readouterr().out
    rc = cli3d.main(
        ["2", "128", "10", "64", "1", "--engine", "bitpack", "--outdir",
         str(tmp_path / "b")]
    )
    assert rc == 0
    a = np.load(tmp_path / "a" / "World3D_of_1.npy")
    b = np.load(tmp_path / "b" / "World3D_of_1.npy")
    np.testing.assert_array_equal(a, b)


def test_mesh_pallas_engine_rejects_sharded_h(capsys):
    rc = cli3d.main(
        ["2", "64", "2", "64", "0", "--mesh", "3d", "--mesh-shape",
         "2,2,2", "--engine", "pallas"]
    )
    assert rc == 255
    assert "H-unsharded" in capsys.readouterr().out


def test_mesh_shape_validation(capsys):
    rc = cli3d.main(
        ["2", "32", "1", "64", "0", "--mesh-shape", "2,1,4"]
    )
    assert rc == 255
    assert "--mesh 3d" in capsys.readouterr().out
    rc = cli3d.main(
        ["2", "32", "1", "64", "0", "--mesh", "3d", "--mesh-shape", "nope"]
    )
    assert rc == 255
    assert "P,R,C" in capsys.readouterr().out
