"""3-D driver surface: engines agree, dumps load, validation fires."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from gol_tpu import cli3d

jax.config.update("jax_platforms", "cpu")


def test_parse_rule3d():
    r = cli3d.parse_rule3d("bays4555")
    assert r.birth == frozenset({5}) and r.survive == frozenset({4, 5})
    r = cli3d.parse_rule3d("B5,6/S4,5,26")
    assert r.birth == frozenset({5, 6})
    assert r.survive == frozenset({4, 5, 26})
    with pytest.raises(ValueError, match="malformed"):
        cli3d.parse_rule3d("5/45")
    with pytest.raises(ValueError, match="> 26"):
        cli3d.parse_rule3d("B27/S")


@pytest.mark.parametrize("engine", ["dense", "bitpack"])
def test_engines_agree_on_dump(tmp_path, engine, capsys):
    rc = cli3d.main(
        ["2", "32", "3", "64", "1", "--engine", engine, "--outdir",
         str(tmp_path / engine)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "TOTAL DURATION" in out and "POPULATION" in out


def test_engine_dumps_are_identical(tmp_path):
    for engine in ("dense", "bitpack"):
        assert (
            cli3d.main(
                ["2", "32", "3", "64", "1", "--engine", engine, "--outdir",
                 str(tmp_path / engine)]
            )
            == 0
        )
    a = np.load(tmp_path / "dense" / "World3D_of_1.npy")
    b = np.load(tmp_path / "bitpack" / "World3D_of_1.npy")
    np.testing.assert_array_equal(a, b)


def test_sharded_3d_cli_matches_single(tmp_path):
    assert (
        cli3d.main(
            ["2", "32", "2", "64", "1", "--mesh", "3d", "--outdir",
             str(tmp_path / "mesh")]
        )
        == 0
    )
    assert (
        cli3d.main(
            ["2", "32", "2", "64", "1", "--engine", "dense", "--outdir",
             str(tmp_path / "single")]
        )
        == 0
    )
    np.testing.assert_array_equal(
        np.load(tmp_path / "mesh" / "World3D_of_1.npy"),
        np.load(tmp_path / "single" / "World3D_of_1.npy"),
    )


def test_validation(capsys):
    assert cli3d.main(["9", "16", "1", "64", "0"]) == 255
    assert "not been implemented" in capsys.readouterr().out
    assert cli3d.main(["2", "16", "1", "64", "0", "--rule", "wat"]) == 255
    assert cli3d.main(["2", "16", "1", "0", "0"]) == 255
    assert cli3d.main(["2", "16"]) == 255  # wrong arg count -> usage


def test_zero_iterations(tmp_path, capsys):
    rc = cli3d.main(
        ["1", "16", "0", "64", "1", "--outdir", str(tmp_path)]
    )
    assert rc == 0
    vol = np.load(tmp_path / "World3D_of_1.npy")
    assert vol.sum() == 16**3


def test_sharded_3d_custom_rule(tmp_path):
    """--mesh 3d + a custom rule through the packed sharded path.

    Size 64 over the 2x2x2 mesh gives x-shards 32 cells wide — exactly
    one packed word — so auto takes compiled_evolve3d_packed (size 32
    would silently fall back to the dense sharded engine)."""
    a = cli3d.main(
        ["2", "64", "2", "64", "1", "--mesh", "3d", "--rule", "B5,6/S4,5",
         "--outdir", str(tmp_path / "mesh")]
    )
    b = cli3d.main(
        ["2", "64", "2", "64", "1", "--engine", "dense", "--rule",
         "B5,6/S4,5", "--outdir", str(tmp_path / "single")]
    )
    assert a == 0 and b == 0
    np.testing.assert_array_equal(
        np.load(tmp_path / "mesh" / "World3D_of_1.npy"),
        np.load(tmp_path / "single" / "World3D_of_1.npy"),
    )


# -- checkpoint / resume (capability parity with the 2-D driver) -------------


def test_cli3d_checkpoint_and_resume_equivalence(tmp_path, capsys):
    """10 straight generations == 4 generations + snapshot + resumed 6."""
    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    out_a = tmp_path / "a"
    out_b = tmp_path / "b"
    ck = tmp_path / "ck"
    assert cli3d.main(
        ["2", "32", "10", "64", "1", "--outdir", str(out_a)]
    ) == 0
    assert cli3d.main(
        ["2", "32", "4", "64", "0", "--checkpoint-every", "4",
         "--checkpoint-dir", str(ck)]
    ) == 0
    resume = ckpt_mod.checkpoint3d_path(str(ck), 4)
    assert cli3d.main(
        ["2", "32", "6", "64", "1", "--resume", resume,
         "--outdir", str(out_b)]
    ) == 0
    import numpy as np_

    a = np_.load(out_a / "World3D_of_1.npy")
    b = np_.load(out_b / "World3D_of_1.npy")
    np_.testing.assert_array_equal(a, b)


def test_cli3d_resume_rule_mismatch_rejected(tmp_path, capsys):
    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    ck = tmp_path / "ck"
    assert cli3d.main(
        ["2", "32", "4", "64", "0", "--checkpoint-every", "4",
         "--checkpoint-dir", str(ck), "--rule", "bays5766"]
    ) == 0
    capsys.readouterr()
    rc = cli3d.main(
        ["2", "32", "2", "64", "0",
         "--resume", ckpt_mod.checkpoint3d_path(str(ck), 4)]
    )
    assert rc == 255
    assert "pass the matching --rule" in capsys.readouterr().out


def test_cli3d_resume_corrupt_snapshot_rejected(tmp_path, capsys):
    import numpy as np_

    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    path = ckpt_mod.checkpoint3d_path(str(tmp_path), 3)
    vol = np_.random.default_rng(0).integers(0, 2, (32, 32, 32), np_.uint8)
    ckpt_mod.save3d(path, vol, 3, "B5/S4,5")
    with np_.load(path) as data:
        arrays = {k: data[k].copy() for k in data.files}
    arrays["volume"][0, 0, 0] ^= 1  # in-range flip
    np_.savez_compressed(path, **arrays)
    capsys.readouterr()
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", path])
    assert rc == 255
    assert "corrupt" in capsys.readouterr().out


def test_cli3d_resume_missing_file_fails_clean(tmp_path, capsys):
    from gol_tpu import cli3d

    rc = cli3d.main(
        ["2", "32", "2", "64", "0", "--resume", str(tmp_path / "nope.npz")]
    )
    assert rc == 255  # OSError path: clean message, no traceback


def test_cli3d_resume_2d_checkpoint_rejected(tmp_path, capsys):
    import numpy as np_

    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    path = ckpt_mod.checkpoint_path(str(tmp_path), 1)
    ckpt_mod.save(path, np_.zeros((8, 8), np_.uint8), 1, num_ranks=1)
    capsys.readouterr()
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", path])
    assert rc == 255
    assert "not a 3-D snapshot" in capsys.readouterr().out


def test_cli3d_resume_truncated_snapshot_fails_clean(tmp_path, capsys):
    from gol_tpu import cli3d

    bad = tmp_path / "trunc.gol3d.npz"
    bad.write_bytes(b"PK\x03\x04 definitely not a real zip")
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", str(bad)])
    assert rc == 255
    assert "not a readable snapshot" in capsys.readouterr().out


def test_cli3d_resume_missing_fingerprint_fails_clean(tmp_path, capsys):
    import numpy as np_

    from gol_tpu import cli3d

    bad = tmp_path / "nofp.gol3d.npz"
    np_.savez_compressed(
        bad, volume=np_.zeros((32, 32, 32), np_.uint8)
    )
    rc = cli3d.main(["2", "32", "2", "64", "0", "--resume", str(bad)])
    assert rc == 255
    assert "missing" in capsys.readouterr().out

def test_mesh_pallas_engine_matches_single_device(tmp_path, capsys):
    """--engine pallas --mesh 3d (H-unsharded shape): the fused sharded
    kernel per shard, byte-compared against the single-device dump."""
    rc = cli3d.main(
        ["2", "128", "10", "64", "1", "--mesh", "3d", "--mesh-shape",
         "2,1,4", "--engine", "pallas", "--outdir", str(tmp_path / "a")]
    )
    assert rc == 0, capsys.readouterr().out
    rc = cli3d.main(
        ["2", "128", "10", "64", "1", "--engine", "bitpack", "--outdir",
         str(tmp_path / "b")]
    )
    assert rc == 0
    a = np.load(tmp_path / "a" / "World3D_of_1.npy")
    b = np.load(tmp_path / "b" / "World3D_of_1.npy")
    np.testing.assert_array_equal(a, b)


def test_mesh_pallas_engine_rejects_sharded_h(capsys):
    rc = cli3d.main(
        ["2", "64", "2", "64", "0", "--mesh", "3d", "--mesh-shape",
         "2,2,2", "--engine", "pallas"]
    )
    assert rc == 255
    assert "H-unsharded" in capsys.readouterr().out


def test_mesh_shape_validation(capsys):
    rc = cli3d.main(
        ["2", "32", "1", "64", "0", "--mesh-shape", "2,1,4"]
    )
    assert rc == 255
    assert "--mesh 3d" in capsys.readouterr().out
    rc = cli3d.main(
        ["2", "32", "1", "64", "0", "--mesh", "3d", "--mesh-shape", "nope"]
    )
    assert rc == 255
    assert "P,R,C" in capsys.readouterr().out

# -- round-3 driver parity: sharded checkpoints, guard, resume ---------------


def test_sharded_checkpoint_and_resume_byte_exact(tmp_path, capsys):
    """Mesh run writes the sharded piece-file format (no monolithic npz,
    no host gather); resume from it == straight run, byte-exact."""

    common = ["2", "64", "10", "64", "1", "--mesh", "3d", "--mesh-shape",
              "2,1,2", "--engine", "bitpack"]
    rc = cli3d.main(common + ["--outdir", str(tmp_path / "straight")])
    assert rc == 0

    rc = cli3d.main(
        ["2", "64", "4", "64", "0", "--mesh", "3d", "--mesh-shape",
         "2,1,2", "--engine", "bitpack", "--checkpoint-every", "4",
         "--checkpoint-dir", str(tmp_path / "ck")]
    )
    assert rc == 0, capsys.readouterr().out
    ckdir = tmp_path / "ck" / "ckpt3d_000000000004.gol3d.d"
    assert ckdir.is_dir()  # the sharded format, not a monolithic npz
    assert (ckdir / "manifest.npz").exists()
    rc = cli3d.main(
        ["2", "64", "6", "64", "1", "--mesh", "3d", "--mesh-shape",
         "2,1,2", "--engine", "bitpack", "--resume", str(ckdir),
         "--outdir", str(tmp_path / "resumed")]
    )
    assert rc == 0, capsys.readouterr().out
    a = np.load(tmp_path / "straight" / "World3D_of_1.npy")
    b = np.load(tmp_path / "resumed" / "World3D_of_1.npy")
    np.testing.assert_array_equal(a, b)
    # Single-device resume from the same sharded checkpoint.
    rc = cli3d.main(
        ["2", "64", "6", "64", "1", "--engine", "bitpack", "--resume",
         str(ckdir), "--outdir", str(tmp_path / "resumed1")]
    )
    assert rc == 0, capsys.readouterr().out
    c = np.load(tmp_path / "resumed1" / "World3D_of_1.npy")
    np.testing.assert_array_equal(a, c)


def test_guarded_run_matches_unguarded(tmp_path, capsys):
    rc = cli3d.main(
        ["2", "32", "9", "64", "1", "--engine", "bitpack",
         "--guard-every", "4", "--outdir", str(tmp_path / "g")]
    )
    assert rc == 0, capsys.readouterr().out
    out = capsys.readouterr().out
    assert "GUARD          : 3 checks, 0 failures, 0 restores" in out
    rc = cli3d.main(
        ["2", "32", "9", "64", "1", "--engine", "bitpack",
         "--outdir", str(tmp_path / "p")]
    )
    assert rc == 0
    np.testing.assert_array_equal(
        np.load(tmp_path / "g" / "World3D_of_1.npy"),
        np.load(tmp_path / "p" / "World3D_of_1.npy"),
    )


def test_guarded_redundant_run(tmp_path, capsys):
    rc = cli3d.main(
        ["2", "32", "8", "64", "1", "--engine", "bitpack",
         "--guard-every", "4", "--guard-redundant",
         "--outdir", str(tmp_path / "r")]
    )
    assert rc == 0, capsys.readouterr().out
    assert "GUARD          : 2 checks" in capsys.readouterr().out
    rc = cli3d.main(
        ["2", "32", "8", "64", "1", "--engine", "dense",
         "--outdir", str(tmp_path / "p")]
    )
    assert rc == 0
    np.testing.assert_array_equal(
        np.load(tmp_path / "r" / "World3D_of_1.npy"),
        np.load(tmp_path / "p" / "World3D_of_1.npy"),
    )


def test_guard_redundant_requires_guard_every(capsys):
    rc = cli3d.main(["2", "32", "4", "64", "0", "--guard-redundant"])
    assert rc == 255
    assert "--guard-every" in capsys.readouterr().out


def test_guard3d_fault_drill():
    """guarded_loop + the 3-D driver's evolvers: an out-of-range flip is
    detected and rolled back; an in-range flip needs the redundant audit."""
    import jax.numpy as jnp

    from gol_tpu.ops import life3d
    from gol_tpu.utils import guard as guard_mod
    from gol_tpu.utils.timing import Stopwatch

    size, rule = 32, cli3d.parse_rule3d("bays4555")
    vol = cli3d.init_volume(2, size)
    compiled, place = cli3d._build_evolver("bitpack", None, 4, rule, size)
    evolvers = {4: (compiled, ())}
    fired = []

    def hook(board, gen):
        if gen == 8 and not fired:
            fired.append(gen)
            return board.at[1, 2, 3].set(jnp.uint8(0xA5))  # out-of-range
        return board

    sw, rep = Stopwatch(), guard_mod.GuardReport()
    board, generation = guard_mod.guarded_loop(
        sw, rep, place(vol), 0, [4, 4, 4], evolvers, None,
        guard_mod.GuardConfig(check_every=4, fault_hook=hook),
    )
    assert generation == 12
    assert rep.failures == 1 and rep.restores == 1 and rep.checks == 4
    ref = jnp.asarray(vol)
    for _ in range(12):
        ref = life3d.step3d(ref)
    np.testing.assert_array_equal(np.asarray(board), np.asarray(ref))


def test_guard3d_redundant_catches_inrange_flip():
    import jax.numpy as jnp

    from gol_tpu.utils import guard as guard_mod
    from gol_tpu.utils.timing import Stopwatch

    size, rule = 32, cli3d.parse_rule3d("bays4555")
    vol = cli3d.init_volume(2, size)
    evolvers = {
        4: (cli3d._build_evolver("bitpack", None, 4, rule, size)[0], ())
    }
    checkers = {
        4: (cli3d._build_evolver("dense", None, 4, rule, size)[0], ())
    }
    fired = []

    def hook(board, gen):
        if gen == 4 and not fired:
            fired.append(gen)
            v = int(board[0, 0, 0])
            return board.at[0, 0, 0].set(jnp.uint8(1 - v))  # IN-range
        return board

    sw, rep = Stopwatch(), guard_mod.GuardReport()
    import jax

    board, generation = guard_mod.guarded_loop(
        sw, rep, jax.device_put(vol), 0, [4, 4], evolvers, checkers,
        guard_mod.GuardConfig(check_every=4, fault_hook=hook, redundant=True),
    )
    assert generation == 8
    assert rep.failures == 1 and rep.restores == 1

def test_resume_from_2d_sharded_dir_clean_error(tmp_path, capsys):
    """Pointing --resume at a 2-D sharded checkpoint dir must exit 255
    with a clean cross-driver message, not a KeyError traceback."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.utils import checkpoint as ckpt_mod

    mesh = mesh_mod.make_mesh_1d(4)
    board = jax.device_put(
        jnp.zeros((32, 32), jnp.uint8), mesh_mod.board_sharding(mesh)
    )
    d = ckpt_mod.sharded_checkpoint_path(str(tmp_path), 3)
    ckpt_mod.save_sharded(d, board, 3, num_ranks=4)
    rc = cli3d.main(
        ["2", "32", "2", "64", "0", "--engine", "dense", "--resume", d]
    )
    assert rc == 255
    assert "3-D sharded checkpoint manifest" in capsys.readouterr().out
