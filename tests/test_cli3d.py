"""3-D driver surface: engines agree, dumps load, validation fires."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from gol_tpu import cli3d

jax.config.update("jax_platforms", "cpu")


def test_parse_rule3d():
    r = cli3d.parse_rule3d("bays4555")
    assert r.birth == frozenset({5}) and r.survive == frozenset({4, 5})
    r = cli3d.parse_rule3d("B5,6/S4,5,26")
    assert r.birth == frozenset({5, 6})
    assert r.survive == frozenset({4, 5, 26})
    with pytest.raises(ValueError, match="malformed"):
        cli3d.parse_rule3d("5/45")
    with pytest.raises(ValueError, match="> 26"):
        cli3d.parse_rule3d("B27/S")


@pytest.mark.parametrize("engine", ["dense", "bitpack"])
def test_engines_agree_on_dump(tmp_path, engine, capsys):
    rc = cli3d.main(
        ["2", "32", "3", "64", "1", "--engine", engine, "--outdir",
         str(tmp_path / engine)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "TOTAL DURATION" in out and "POPULATION" in out


def test_engine_dumps_are_identical(tmp_path):
    for engine in ("dense", "bitpack"):
        assert (
            cli3d.main(
                ["2", "32", "3", "64", "1", "--engine", engine, "--outdir",
                 str(tmp_path / engine)]
            )
            == 0
        )
    a = np.load(tmp_path / "dense" / "World3D_of_1.npy")
    b = np.load(tmp_path / "bitpack" / "World3D_of_1.npy")
    np.testing.assert_array_equal(a, b)


def test_sharded_3d_cli_matches_single(tmp_path):
    assert (
        cli3d.main(
            ["2", "32", "2", "64", "1", "--mesh", "3d", "--outdir",
             str(tmp_path / "mesh")]
        )
        == 0
    )
    assert (
        cli3d.main(
            ["2", "32", "2", "64", "1", "--engine", "dense", "--outdir",
             str(tmp_path / "single")]
        )
        == 0
    )
    np.testing.assert_array_equal(
        np.load(tmp_path / "mesh" / "World3D_of_1.npy"),
        np.load(tmp_path / "single" / "World3D_of_1.npy"),
    )


def test_validation(capsys):
    assert cli3d.main(["9", "16", "1", "64", "0"]) == 255
    assert "not been implemented" in capsys.readouterr().out
    assert cli3d.main(["2", "16", "1", "64", "0", "--rule", "wat"]) == 255
    assert cli3d.main(["2", "16", "1", "0", "0"]) == 255
    assert cli3d.main(["2", "16"]) == 255  # wrong arg count -> usage


def test_zero_iterations(tmp_path, capsys):
    rc = cli3d.main(
        ["1", "16", "0", "64", "1", "--outdir", str(tmp_path)]
    )
    assert rc == 0
    vol = np.load(tmp_path / "World3D_of_1.npy")
    assert vol.sum() == 16**3


def test_sharded_3d_custom_rule(tmp_path):
    """--mesh 3d + a custom rule through the packed sharded path.

    Size 64 over the 2x2x2 mesh gives x-shards 32 cells wide — exactly
    one packed word — so auto takes compiled_evolve3d_packed (size 32
    would silently fall back to the dense sharded engine)."""
    a = cli3d.main(
        ["2", "64", "2", "64", "1", "--mesh", "3d", "--rule", "B5,6/S4,5",
         "--outdir", str(tmp_path / "mesh")]
    )
    b = cli3d.main(
        ["2", "64", "2", "64", "1", "--engine", "dense", "--rule",
         "B5,6/S4,5", "--outdir", str(tmp_path / "single")]
    )
    assert a == 0 and b == 0
    np.testing.assert_array_equal(
        np.load(tmp_path / "mesh" / "World3D_of_1.npy"),
        np.load(tmp_path / "single" / "World3D_of_1.npy"),
    )
