"""Kill-9 chaos drill (slow tier): the acceptance test for the process
tier, run for real against OS processes.

For each config (2-D plain / guard / stats, and a 3-D run), a supervised
child is:

1. **SIGTERM'd at a random chunk** (after a random 1–3 checkpoints have
   landed) — it must exit 75 with a final boundary checkpoint;
2. relaunched, then **SIGKILL'd mid-checkpoint-write** — the drill holds
   the tmp→rename window open with a ``checkpoint.rename_delay`` fault
   plan entry (``GOL_FAULT_PLAN``, inherited by every supervised child;
   the old ``GOL_CKPT_TEST_WRITE_DELAY`` env var remains a documented
   alias, pinned by tests/test_faults.py) and fires the moment a
   ``.tmp.npz`` appears, so the kill lands inside an actual write and
   leaves a torn tmp on disk;
3. relaunched again and left to finish.

The assertion is the whole point of the tier: the final dump is
**byte-identical** to the same run executed uninterrupted, and the torn
tmp was never resumed from.  Marked ``slow`` (tens of seconds of real
subprocess churn); the tier-1 gate runs the lighter
scripts/resilience_drill.py smoke instead.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _env(write_delay=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    if write_delay is not None:
        # The rename-gap hook as a declarative fault-plan entry
        # (armed on every attempt and every save — the kill window
        # must stay open whichever relaunch the SIGKILL phase hits).
        env["GOL_FAULT_PLAN"] = json.dumps(
            {
                "faults": [
                    {"site": "checkpoint.rename_delay",
                     "delay_s": write_delay, "count": -1, "attempts": -1}
                ]
            }
        )
    return env


def _read_manifest(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _wait(cond, timeout=180, interval=0.02, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _running_pid(manifest, idx):
    m = _read_manifest(manifest)
    if not m:
        return None
    att = m.get("attempts") or []
    if len(att) > idx and att[idx].get("pid") and att[idx].get(
        "exit_code"
    ) is None:
        return att[idx]["pid"]
    return None


def _snapshots(ck):
    if not os.path.isdir(ck):
        return []
    return [
        n for n in os.listdir(ck)
        if n.startswith("ckpt") and not n.endswith(".tmp.npz")
    ]


def _tmps(ck):
    if not os.path.isdir(ck):
        return []
    return [n for n in os.listdir(ck) if n.endswith(".tmp.npz")]


def _drill(tmp_path, module, world, extra, dump_name):
    ref = tmp_path / "ref"
    out = tmp_path / "out"
    ck = str(tmp_path / "ck")
    manifest = str(tmp_path / "m.json")
    ref.mkdir()
    out.mkdir()

    # Uninterrupted reference.
    subprocess.run(
        [sys.executable, "-m", module, *world, "--outdir", str(ref)],
        env=_env(), cwd=REPO, check=True,
    )

    child = [
        sys.executable, "-m", module, *world,
        "--outdir", str(out),
        "--checkpoint-every", "2", "--checkpoint-dir", ck,
        "--auto-resume", *extra,
    ]
    sup = subprocess.Popen(
        [sys.executable, "-m", "gol_tpu.resilience", "supervise",
         "--max-restarts", "4", "--backoff-base", "0",
         "--manifest", manifest, "--checkpoint-dir", ck, "--", *child],
        env=_env(write_delay=0.3), cwd=REPO,
    )
    try:
        # Phase 1: SIGTERM at a random chunk — after 1..3 checkpoints.
        k = random.randint(1, 3)
        pid0 = _wait(
            lambda: (
                _running_pid(manifest, 0)
                if len(_snapshots(ck)) >= k
                else None
            ),
            what=f"attempt 0 with >= {k} checkpoints",
        )
        os.kill(pid0, signal.SIGTERM)

        # Phase 2: SIGKILL attempt 1 mid-checkpoint-write (a .tmp file
        # exists exactly while the held-open write window is live).
        pid1 = _wait(
            lambda: _running_pid(manifest, 1), what="attempt 1 to spawn"
        )
        _wait(lambda: _tmps(ck), what="an in-flight .tmp checkpoint")
        os.kill(pid1, signal.SIGKILL)

        rc = sup.wait(timeout=300)
    finally:
        if sup.poll() is None:
            sup.kill()
    assert rc == 0, f"supervisor exited {rc}; manifest: {_read_manifest(manifest)}"

    m = _read_manifest(manifest)
    codes = [a["exit_code"] for a in m["attempts"]]
    assert codes[0] == 75, f"SIGTERM attempt should exit 75, got {codes}"
    assert codes[1] == -signal.SIGKILL, (
        f"SIGKILL attempt should die on signal 9, got {codes}"
    )
    assert codes[-1] == 0 and m["finished"]
    # The kill landed inside a write (the drill saw the .tmp), yet every
    # snapshot that exists at a real snapshot path fully verifies — the
    # torn write was never promoted past its tmp name.
    from gol_tpu.utils import checkpoint as ckpt

    for name in _snapshots(ck):
        ckpt.verify_snapshot(os.path.join(ck, name))

    a = (ref / dump_name).read_bytes()
    b = (out / dump_name).read_bytes()
    assert a == b, "final grid differs from the uninterrupted run"


def test_chaos_2d_plain(tmp_path):
    _drill(
        tmp_path, "gol_tpu", ["4", "256", "40", "512", "1"], [],
        "Rank_0_of_1.txt",
    )


def test_chaos_2d_guarded(tmp_path):
    _drill(
        tmp_path, "gol_tpu", ["4", "256", "40", "512", "1"],
        ["--guard-every", "2"],
        "Rank_0_of_1.txt",
    )


def test_chaos_2d_stats(tmp_path):
    tm = str(tmp_path / "tm")
    _drill(
        tmp_path, "gol_tpu", ["4", "256", "40", "512", "1"],
        ["--stats", "--telemetry", tm],
        "Rank_0_of_1.txt",
    )
    # Every attempt's stream landed (unique default run-ids per process).
    import glob

    assert len(glob.glob(os.path.join(tm, "*.rank0.jsonl"))) >= 1


def test_chaos_3d(tmp_path):
    _drill(
        tmp_path, "gol_tpu.cli3d", ["2", "64", "24", "64", "1"], [],
        "World3D_of_1.npy",
    )


def _tmps_recursive(ck):
    """In-flight ``.tmp`` writes anywhere under the checkpoint dir —
    sharded snapshots nest their piece/manifest tmps inside the
    ``ckpt_*.gol.d`` directory."""
    found = []
    for root, _, names in os.walk(ck):
        found.extend(
            os.path.join(root, n) for n in names if n.endswith(".tmp.npz")
        )
    return found


def test_chaos_shrink_then_resume(tmp_path):
    """Elastic-mesh chaos (docs/RESILIENCE.md): a supervised 1-D-mesh run
    is SIGTERM'd, relaunched on a device count the board cannot tile —
    the shrink policy (GOL_ALLOW_SHRINK, exported by the supervisor)
    must drop it to a smaller mesh and reshard the 4-shard snapshot onto
    it — then SIGKILL'd mid-sharded-checkpoint-write, and relaunched
    again to finish.  The final dump must be byte-identical to an
    uninterrupted (unmeshed) run, and telemetry must carry the v7
    ``reshard`` event naming the 1d 4x1 → 1d 2x1 repartition.
    """
    ref = tmp_path / "ref"
    out = tmp_path / "out"
    ck = str(tmp_path / "ck")
    tm = str(tmp_path / "tm")
    manifest = str(tmp_path / "m.json")
    ref.mkdir()
    out.mkdir()
    world = ["4", "256", "40", "512", "1"]

    # Uninterrupted reference (no mesh — mesh-independence is pinned
    # elsewhere; byte-equality against it is the stronger assertion).
    subprocess.run(
        [sys.executable, "-m", "gol_tpu", *world, "--outdir", str(ref)],
        env=_env(), cwd=REPO, check=True,
    )

    # The shrink shim: attempt 0 comes up with 4 CPU devices, every
    # relaunch with 3 — a count the 256-row board cannot tile, forcing
    # the elastic shrink down to 2.  XLA_FLAGS must be set before jax
    # imports, hence a wrapper process instead of supervisor env.
    shim = tmp_path / "shim.py"
    shim.write_text(
        "import os, runpy, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "n = 4 if os.environ.get('GOL_RESTART_ATTEMPT', '0') == '0' else 3\n"
        "os.environ['XLA_FLAGS'] = (\n"
        "    f'--xla_force_host_platform_device_count={n}'\n"
        ")\n"
        "runpy.run_module('gol_tpu', run_name='__main__', alter_sys=True)\n"
    )
    child = [
        sys.executable, str(shim), *world,
        "--outdir", str(out),
        "--mesh", "1d", "--sharded-snapshots",
        "--checkpoint-every", "2", "--checkpoint-dir", ck,
        "--auto-resume", "--telemetry", tm,
    ]
    sup = subprocess.Popen(
        [sys.executable, "-m", "gol_tpu.resilience", "supervise",
         "--max-restarts", "4", "--backoff-base", "0",
         "--manifest", manifest, "--checkpoint-dir", ck, "--", *child],
        env=_env(write_delay=0.3), cwd=REPO,
    )
    try:
        # Phase 1: SIGTERM the 4-device attempt once a snapshot dir has
        # a manifest (the sharded promotion point).
        def _complete():
            return [
                n for n in _snapshots(ck)
                if os.path.exists(os.path.join(ck, n, "manifest.npz"))
            ]

        pid0 = _wait(
            lambda: _running_pid(manifest, 0) if _complete() else None,
            what="attempt 0 with a complete sharded checkpoint",
        )
        os.kill(pid0, signal.SIGTERM)

        # Phase 2: SIGKILL the shrunk attempt mid-sharded-write.
        pid1 = _wait(
            lambda: _running_pid(manifest, 1), what="attempt 1 to spawn"
        )
        before = set(_tmps_recursive(ck))
        _wait(
            lambda: set(_tmps_recursive(ck)) - before,
            what="an in-flight sharded .tmp write",
        )
        os.kill(pid1, signal.SIGKILL)

        rc = sup.wait(timeout=300)
    finally:
        if sup.poll() is None:
            sup.kill()
    assert rc == 0, f"supervisor exited {rc}; manifest: {_read_manifest(manifest)}"

    m = _read_manifest(manifest)
    codes = [a["exit_code"] for a in m["attempts"]]
    assert codes[0] == 75, f"SIGTERM attempt should exit 75, got {codes}"
    assert codes[1] == -signal.SIGKILL, (
        f"SIGKILL attempt should die on signal 9, got {codes}"
    )
    assert codes[-1] == 0 and m["finished"]

    # Every promoted snapshot (manifest present) fully verifies; the
    # torn mid-write dir was never promoted past its tmp names.
    from gol_tpu.utils import checkpoint as ckpt

    verified = 0
    for name in _snapshots(ck):
        if os.path.exists(os.path.join(ck, name, "manifest.npz")):
            ckpt.verify_snapshot(os.path.join(ck, name))
            verified += 1
    assert verified, "no promoted snapshot survived the drill"

    # The shrink really happened and was repartitioned, not restarted:
    # some attempt's stream carries the v7 reshard event 1d 4x1 -> 1d 2x1.
    import glob

    reshards = []
    for path in glob.glob(os.path.join(tm, "*.rank0.jsonl")):
        for line in open(path):
            rec = json.loads(line)
            if rec.get("event") == "reshard":
                reshards.append(rec)
    assert any(
        r["src_mesh"] == {"kind": "1d", "rows": 4, "cols": 1}
        and r["dst_mesh"] == {"kind": "1d", "rows": 2, "cols": 1}
        for r in reshards
    ), f"expected a 1d 4x1 -> 1d 2x1 reshard event, got {reshards}"

    a = (ref / "Rank_0_of_1.txt").read_bytes()
    b = (out / "Rank_0_of_1.txt").read_bytes()
    assert a == b, "final grid differs from the uninterrupted run"
