"""Native C++ runtime helpers vs. their Python arbiters (byte-for-byte)."""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from gol_tpu.utils import io as gol_io
from gol_tpu.utils import native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def build_native():
    if shutil.which("make") and shutil.which("g++"):
        subprocess.run(
            ["make", "-C", os.path.join(REPO, "native")],
            check=False,
            capture_output=True,
        )
    # Reset the lazy loader so this module sees a lib built after import.
    native._lib = None
    native._load_attempted = False
    yield


needs_native = pytest.mark.skipif(
    not (shutil.which("g++") and shutil.which("make")),
    reason="native toolchain unavailable",
)


@needs_native
def test_native_available():
    assert native.available()


@needs_native
def test_native_format_matches_python():
    rng = np.random.default_rng(0)
    for shape, rank in [((3, 3), 0), ((12, 7), 4), ((120, 5), 1)]:
        block = rng.integers(0, 2, shape).astype(np.uint8)
        assert native.format_world(block, rank) == gol_io.format_world(block, rank)


@needs_native
def test_native_writer_matches_python(tmp_path):
    rng = np.random.default_rng(1)
    block = rng.integers(0, 2, (16, 9)).astype(np.uint8)
    native.write_rank_file(str(tmp_path / "n.txt"), block, 2)
    with open(tmp_path / "n.txt", "rb") as f:
        got = f.read()
    assert got == gol_io.format_rank_file(block, 2)


@needs_native
def test_native_writer_used_by_io_layer(tmp_path):
    """write_rank_file(use_native=True) and =False produce identical files."""
    block = np.random.default_rng(2).integers(0, 2, (8, 8)).astype(np.uint8)
    pa = gol_io.write_rank_file(block, 0, 1, str(tmp_path / "a"), use_native=True)
    pb = gol_io.write_rank_file(block, 0, 1, str(tmp_path / "b"), use_native=False)
    assert open(pa, "rb").read() == open(pb, "rb").read()


@needs_native
def test_native_pack_unpack_roundtrip():
    rng = np.random.default_rng(3)
    cells = rng.integers(0, 2, 32 * 17).astype(np.uint8)
    words = native.pack_bits(cells)
    assert words.dtype == np.uint32 and words.size == 17
    # Bit i of word j = cell j*32 + i.
    expected0 = sum(int(cells[b]) << b for b in range(32))
    assert int(words[0]) == expected0
    np.testing.assert_array_equal(native.unpack_bits(words), cells)


@needs_native
def test_native_driver_execs_runtime(tmp_path):
    """The C++ `gol` binary: usage on wrong argc; exec's the runtime on 5."""
    gol = os.path.join(REPO, "native", "gol")
    assert os.path.exists(gol)
    bad = subprocess.run([gol, "1", "2"], capture_output=True, text=True)
    assert bad.returncode == 255  # exit(-1)
    assert "5 arguments" in bad.stdout

    env = dict(os.environ)
    env["GOL_PYTHON"] = sys.executable
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    ok = subprocess.run(
        [gol, "4", "8", "2", "64", "1", "--outdir", str(tmp_path)],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert ok.returncode == 0, ok.stderr
    assert "TOTAL DURATION : " in ok.stdout
    assert (tmp_path / "Rank_0_of_1.txt").exists()
