"""Roofline attribution arithmetic: audited constants, recompute sums,
and the degenerate cases that keep the numbers meaningful."""

import pytest

from gol_tpu.utils import roofline


def test_flat_kernel_no_recompute():
    """k=1, huge tile: the recompute factor approaches 1 and ops/word
    approaches the flat per-word count."""
    r = roofline.roofline_2d(1e12, tile=1024, k=1)
    flat = roofline.OPS_2D_HSUM_PER_EXT_ROW + roofline.OPS_2D_RULE_PER_OUT_ROW
    assert r.ops_per_useful_word == pytest.approx(flat, rel=0.01)
    assert r.recompute_factor == pytest.approx(1.0, abs=0.01)


def test_recompute_grows_with_depth_and_shrinks_with_tile():
    shallow = roofline.recompute_2d(tile=128, k=8)
    deep = roofline.recompute_2d(tile=128, k=32)
    wide = roofline.recompute_2d(tile=256, k=8)
    assert 1.0 < shallow < deep
    assert wide < shallow
    # Exact closed form: sum(t + 2(k-j)) / (t*k) = 1 + (k+1)/t.
    assert shallow == pytest.approx(1 + 9 / 128)


def test_bench_roofline_matches_engine_pickers():
    """The attribution must use the exact tile/k the benchmarked engine
    picks, not assumptions that can drift."""
    from gol_tpu.ops import bitlife, pallas_bitlife

    r = roofline.bench_roofline_2d(1.85e12, 16384, 16384, 10240)
    tile = pallas_bitlife.pick_tile(
        16384, bitlife.packed_width(16384), pallas_bitlife._BLOCK_TILE
    )
    k = pallas_bitlife._pick_block(10240, tile)
    assert r.ops_per_useful_word == pytest.approx(
        roofline.ops_2d_per_useful_word(tile, k)
    )
    # The round-2 headline rate lands at a plausible VPU fraction —
    # neither >1 (impossible) nor <0.2 (which would mean the op model or
    # the measurement is broken).
    assert 0.3 < r.mfu < 1.0


def test_3d_wt_recompute_includes_both_axes():
    r = roofline.roofline_3d_wt(2.4e11, tile_d=32, tile_w=4, k=8)
    # word factor 6/4 = 1.5; plane factor mean of (32 + 2(8-j))/32.
    word = (4 + 2) / 4
    plane = sum(32 + 2 * (8 - j) for j in range(8)) / (32 * 8)
    assert r.recompute_factor == pytest.approx(word * plane)
    assert 0.2 < r.mfu < 1.0


def test_folded_costs_more_per_row():
    plain = roofline.ops_2d_per_useful_word(128, 8)
    folded = roofline.ops_2d_per_useful_word(128, 8, folded=True)
    assert folded > plain
    assert (folded - plain) < 5  # ~4 extra ops on the hsum stage

def test_ring_attribution_matches_engine_tiling():
    """The ring attribution must mirror the engine's own shard/fold tile
    derivation — pinned against hand-derived expected configurations, not
    by re-running the attribution's implementation."""
    # Wide single-device ring at the bench geometry: nw=512 fills lanes,
    # no fold; engine defaults tile_hint=1024 (r5), halo_depth=8 — the
    # VMEM budget at nw=512 caps the tile at 256.
    r = roofline.bench_roofline_2d_ring(1.8e12, 16384, 16384)
    assert r.ops_per_useful_word == pytest.approx(
        roofline.ops_2d_per_useful_word(256, 8)
    )
    # Folded narrow board: nw=32 -> fold=4; the engine tiles the FOLDED
    # height 640/4=160 (capped by the height itself under the 1024
    # hint), not the unfolded pick(640, 32).
    r = roofline.bench_roofline_2d_ring(1e12, 640, 1024)
    assert r.ops_per_useful_word == pytest.approx(
        roofline.ops_2d_per_useful_word(160, 8, folded=True)
    )
    # Multi-device ring tiles the shard height, not the global height:
    # 4 devices over 512 rows -> shard 128 -> tile 128 even though the
    # global height would allow bigger windows.
    r = roofline.bench_roofline_2d_ring(1e12, 512, 16384, num_devices=4)
    assert r.ops_per_useful_word == pytest.approx(
        roofline.ops_2d_per_useful_word(128, 8)
    )


def test_folded_recompute_factor_isolates_blocking():
    """k=1 folded: recompute factor ~1 even though folded rows cost more
    — fold overhead must not masquerade as halo recompute."""
    r = roofline.roofline_2d(1e12, tile=1024, k=1, folded=True)
    assert r.recompute_factor == pytest.approx(1.0, abs=0.01)

def test_ring_attribution_rejects_unfoldable_geometry():
    """Geometries the engine cannot run must not get an attribution."""
    with pytest.raises(ValueError, match="lane-fold"):
        roofline.bench_roofline_2d_ring(1e12, 648, 1024)


def test_fit_overhead_two_point():
    """The r5 tunnel-overhead fit: T(n) = a + b*n recovered exactly from
    two points (shared by bench.py and the exp_*_fit scripts)."""
    from gol_tpu.utils.timing import fit_overhead

    a, b = fit_overhead({1024: 0.25 + 1024 * 1e-4, 8192: 0.25 + 8192 * 1e-4})
    assert a == pytest.approx(0.25)
    assert b == pytest.approx(1e-4)
    # More than two lengths: the fit uses the extremes.
    a, b = fit_overhead({10: 1.1, 20: 1.2, 110: 2.1})
    assert a == pytest.approx(1.0)
    with pytest.raises(ValueError, match="loop lengths"):
        fit_overhead({100: 1.0})
