"""Multi-host layer tests.

Two tiers:

1. In-process: writer planning, shard assembly, and gather fallback on the
   8-device CPU mesh (single process, all shards addressable).
2. Real multi-process: two OS processes connected via
   ``jax.distributed.initialize`` (Gloo collectives between them — the DCN
   stand-in), running the full CLI; their combined per-host dump files are
   byte-compared against a single-process run.  This is the test the
   reference never had for its MPI tier (SURVEY §4 / bug B1).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax

from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import multihost
from gol_tpu.utils import io as gol_io

jax.config.update("jax_platforms", "cpu")


def _rand_board(h, w, seed=0):
    return np.random.default_rng(seed).integers(0, 2, (h, w), dtype=np.uint8)


def test_topology_single_process():
    topo = multihost.topology()
    assert topo.process_index == 0
    assert topo.process_count == 1
    assert topo.is_coordinator
    assert topo.global_device_count == len(jax.devices())
    assert topo.local_device_count == topo.global_device_count


def test_init_multihost_noop():
    topo = multihost.init_multihost()
    assert topo.process_count == 1


@pytest.mark.parametrize(
    "kwargs",
    [
        dict(coordinator_address="localhost:1"),
        dict(num_processes=2),
        dict(process_id=1),
        dict(coordinator_address="localhost:1", num_processes=2),
        dict(num_processes=2, process_id=0),
        dict(local_device_ids=[0]),
    ],
)
def test_init_multihost_partial_flags_rejected(kwargs):
    # A worker missing one flag must fail loudly, not run as its own
    # single-process job and clobber the real job's output files.
    with pytest.raises(ValueError, match="together"):
        multihost.init_multihost(**kwargs)


def test_cli_multiprocess_requires_mesh(monkeypatch, capsys):
    from gol_tpu import cli

    monkeypatch.setattr(
        multihost,
        "init_multihost",
        lambda **kw: multihost.HostTopology(0, 2, 2, 4),
    )
    rc = cli.main(["4", "8", "1", "16", "0"])
    assert rc == 255
    assert "requires a device mesh" in capsys.readouterr().out


@pytest.mark.parametrize("num_ranks", [1, 2, 4, 8, 16])
def test_plan_all_ranks_covered_single_process(num_ranks):
    mesh = mesh_mod.make_mesh_1d()
    board = jax.device_put(
        _rand_board(32, 16), mesh_mod.board_sharding(mesh)
    )
    writers, gather = multihost.plan_rank_writers(
        board.sharding, board.shape, num_ranks
    )
    assert gather == []
    assert writers == {r: 0 for r in range(num_ranks)}


@pytest.mark.parametrize("mesh_kind", ["1d", "2d"])
@pytest.mark.parametrize("num_ranks", [2, 4, 16])
def test_host_dumps_match_gathered_dumps(tmp_path, mesh_kind, num_ranks):
    mesh = (
        mesh_mod.make_mesh_1d() if mesh_kind == "1d" else mesh_mod.make_mesh_2d()
    )
    board_np = _rand_board(32, 16, seed=3)
    board = jax.device_put(board_np, mesh_mod.board_sharding(mesh))

    a = tmp_path / "host"
    b = tmp_path / "gathered"
    written = multihost.write_host_dumps(board, num_ranks, str(a))
    gol_io.write_world_dumps(board_np, num_ranks, str(b))

    assert len(written) == num_ranks
    for r in range(num_ranks):
        name = gol_io.rank_filename(r, num_ranks)
        assert (a / name).read_bytes() == (b / name).read_bytes()


def test_host_dumps_plain_numpy_board(tmp_path):
    board_np = _rand_board(16, 8, seed=5)
    a = tmp_path / "plain"
    b = tmp_path / "ref"
    multihost.write_host_dumps(board_np, 4, str(a))
    gol_io.write_world_dumps(board_np, 4, str(b))
    for r in range(4):
        name = gol_io.rank_filename(r, 4)
        assert (a / name).read_bytes() == (b / name).read_bytes()


def test_fetch_global_roundtrip():
    mesh = mesh_mod.make_mesh_2d()
    board_np = _rand_board(16, 16, seed=7)
    board = jax.device_put(board_np, mesh_mod.board_sharding(mesh))
    np.testing.assert_array_equal(multihost.fetch_global(board), board_np)


def test_indivisible_rank_count_rejected():
    board = jax.device_put(_rand_board(32, 16))
    with pytest.raises(ValueError, match="not divisible"):
        multihost.write_host_dumps(board, 5)


# -- real two-process tier ---------------------------------------------------

_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gol_tpu import compat as _compat
    _compat.set_cpu_device_count(2)
    from gol_tpu import cli
    from gol_tpu.utils import checkpoint as ckpt_mod
    pid = sys.argv[1]
    rc = cli.main([
        "4", "8", "5", "16", "1",
        "--ranks", "4", "--mesh", "1d",
        "--coordinator", sys.argv[2],
        "--num-processes", "2", "--process-id", pid,
        "--outdir", sys.argv[3],
        "--checkpoint-every", "3", "--checkpoint-dir", sys.argv[4],
    ])
    if rc == 0:
        # Resume the job from the sharded gen-3 checkpoint for the
        # remaining 2 generations (jax.distributed is already connected;
        # the second run reuses the live topology).  Each host reads only
        # its own rows back (make_array_from_callback).
        rc = cli.main([
            "4", "8", "2", "16", "1",
            "--ranks", "4", "--mesh", "1d",
            "--outdir", sys.argv[5],
            "--resume", ckpt_mod.sharded_checkpoint_path(sys.argv[4], 3),
        ])
    sys.exit(rc)
    """
)

# 2-D mesh over 2 processes with a single logical rank: the rank's rows
# span both hosts, so neither covers it alone — the dump must take the
# collective gather fallback (process 0 writes).  --guard-every exercises
# the audit + last-good snapshotting across processes (replicated scalars,
# fetch_global all-gathers).
_WORKER_2D_GUARDED = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gol_tpu import compat as _compat
    _compat.set_cpu_device_count(2)
    from gol_tpu import cli
    pid = sys.argv[1]
    rc = cli.main([
        "4", "16", "5", "16", "1",
        "--ranks", "1", "--mesh", "2d",
        "--coordinator", sys.argv[2],
        "--num-processes", "2", "--process-id", pid,
        "--outdir", sys.argv[3],
        "--guard-every", "2",
    ])
    sys.exit(rc)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_two_workers(worker_src: str, argv_tail) -> list:
    """Launch two coordinator-connected worker processes, return
    [(rc, stdout, stderr), ...].  Workers are killed on timeout/failure so
    a deadlocked jax.distributed barrier can't leak processes holding the
    port for the rest of the session."""
    coord = f"localhost:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers pick their own device counts
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, str(i), coord, *argv_tail],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            cwd=repo,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out.decode(), err.decode()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
    return outs


def test_two_process_cli_matches_single_process(tmp_path):
    """Full CLI across 2 processes (4 global devices): ppermute halo rings
    over the process boundary, per-host rank-file writes, a *sharded*
    multi-host checkpoint (each host writes only its own rows; no
    all-gather) and a cross-process sharded resume — outputs
    byte-identical to the single-process run."""
    out_mh = tmp_path / "mh"
    out_rs = tmp_path / "rs"
    out_sp = tmp_path / "sp"
    ckpt = tmp_path / "ckpt"
    out_mh.mkdir()
    out_rs.mkdir()

    outs = _run_two_workers(_WORKER, [str(out_mh), str(ckpt), str(out_rs)])

    # Only the coordinator reports (reference: rank 0, gol-main.c:121-128).
    assert "TOTAL DURATION" in outs[0][1]
    assert "TOTAL DURATION" not in outs[1][1]

    # Single-process run with the same world, different dir.
    from gol_tpu import cli

    rc = cli.main(
        ["4", "8", "5", "16", "1", "--ranks", "4", "--outdir", str(out_sp)]
    )
    assert rc == 0

    for r in range(4):
        name = gol_io.rank_filename(r, 4)
        sp = (out_sp / name).read_bytes()
        assert (out_mh / name).read_bytes() == sp, (
            f"rank {r} dump differs across process counts"
        )
        # The resumed job (gen 3 checkpoint + 2 generations) must land on
        # the same world as the straight 5-generation run.
        assert (out_rs / name).read_bytes() == sp, (
            f"rank {r} dump differs after sharded resume"
        )

    # The checkpoint is the sharded format: one piece file per process,
    # each holding only that host's rows — no host assembled the board.
    from gol_tpu.utils import checkpoint as ckpt_mod

    d = ckpt_mod.sharded_checkpoint_path(str(ckpt), 3)
    meta = ckpt_mod.load_sharded_meta(d)
    assert meta.generation == 3 and meta.shape == (32, 8)
    piece_rows = {0: [], 1: []}
    for (r0, r1, _, _), proc in zip(meta.rects, meta.procs):
        piece_rows[int(proc)].append((int(r0), int(r1)))
    # 4 global devices = 2 per process; rows [0,16) on proc 0, [16,32) on 1.
    assert all(r1 <= 16 for _, r1 in piece_rows[0])
    assert all(r0 >= 16 for r0, _ in piece_rows[1])
    board = ckpt_mod.read_sharded_region(
        d, meta, (slice(None), slice(None))
    )
    assert board.shape == (32, 8)


def test_two_process_2d_mesh_guarded_gather_dump(tmp_path):
    """2-D mesh across 2 processes + --guard-every: the single rank's rows
    span both hosts, forcing the collective gather-fallback dump; audits
    and last-good snapshots run multi-process.  Output byte-matches the
    single-process run."""
    out_mh = tmp_path / "mh"
    out_sp = tmp_path / "sp"
    out_mh.mkdir()

    outs = _run_two_workers(_WORKER_2D_GUARDED, [str(out_mh)])
    assert "GUARD          : 3 checks, 0 failures, 0 restores" in outs[0][1]
    assert "GUARD" not in outs[1][1]  # only the coordinator reports

    from gol_tpu import cli

    assert (
        cli.main(["4", "16", "5", "16", "1", "--ranks", "1", "--outdir",
                  str(out_sp)])
        == 0
    )
    name = gol_io.rank_filename(0, 1)
    assert (out_mh / name).read_bytes() == (out_sp / name).read_bytes()


# The flagship engine (fused Pallas kernel per shard, interpret mode on
# CPU) across a REAL process boundary: ppermute ghost bands over Gloo feed
# the kernel's no-wrap path on each host.
_WORKER_PALLAS = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gol_tpu import compat as _compat
    _compat.set_cpu_device_count(2)
    from gol_tpu import cli
    pid = sys.argv[1]
    rc = cli.main([
        "4", "32", "9", "16", "1",
        "--ranks", "4", "--mesh", "1d", "--engine", "pallas_bitpack",
        "--coordinator", sys.argv[2],
        "--num-processes", "2", "--process-id", pid,
        "--outdir", sys.argv[3],
    ])
    sys.exit(rc)
    """
)

# Every round-2 subsystem composed in one job: the flagship fused-Pallas
# engine sharded across 2 OS processes, the cross-engine redundancy audit
# (checker = dense, compiled multi-process in lockstep), sharded
# checkpoints (per-host pieces), and a cross-process sharded resume of the
# remaining generations.  Shard height 64 >= 2*8+8 also permits overlap,
# but the guard path is the one under test here.
_WORKER_KITCHEN_SINK = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gol_tpu import compat as _compat
    _compat.set_cpu_device_count(2)
    from gol_tpu import cli
    from gol_tpu.utils import checkpoint as ckpt_mod
    pid = sys.argv[1]
    rc = cli.main([
        "4", "64", "8", "16", "0",
        "--ranks", "4", "--mesh", "1d", "--engine", "pallas_bitpack",
        "--coordinator", sys.argv[2],
        "--num-processes", "2", "--process-id", pid,
        "--guard-every", "4", "--guard-redundant",
        "--checkpoint-every", "4", "--checkpoint-dir", sys.argv[3],
    ])
    if rc == 0:
        rc = cli.main([
            "4", "64", "8", "16", "1",
            "--ranks", "4", "--mesh", "1d", "--engine", "pallas_bitpack",
            "--guard-every", "4", "--guard-redundant",
            "--outdir", sys.argv[4],
            "--resume", ckpt_mod.sharded_checkpoint_path(sys.argv[3], 8),
        ])
    sys.exit(rc)
    """
)


def test_two_process_kitchen_sink(tmp_path):
    """Flagship engine + redundant guard + sharded checkpoint + sharded
    resume, all in one 2-process job; final dumps byte-match the
    straight single-process run of the same 16 generations."""
    ck = tmp_path / "ck"
    out_mh = tmp_path / "mh"
    out_sp = tmp_path / "sp"
    out_mh.mkdir()

    outs = _run_two_workers(_WORKER_KITCHEN_SINK, [str(ck), str(out_mh)])
    assert "GUARD          : 2 checks, 0 failures, 0 restores" in outs[0][1]

    from gol_tpu import cli

    assert (
        cli.main(
            ["4", "64", "16", "16", "1", "--ranks", "4",
             "--outdir", str(out_sp)]
        )
        == 0
    )
    for r in range(4):
        name = gol_io.rank_filename(r, 4)
        assert (out_mh / name).read_bytes() == (out_sp / name).read_bytes()


def test_two_process_flagship_pallas_engine(tmp_path):
    out_mh = tmp_path / "mh"
    out_sp = tmp_path / "sp"
    out_mh.mkdir()
    _run_two_workers(_WORKER_PALLAS, [str(out_mh)])

    from gol_tpu import cli

    assert (
        cli.main(["4", "32", "9", "16", "1", "--ranks", "4", "--outdir",
                  str(out_sp)])
        == 0
    )
    for r in range(4):
        name = gol_io.rank_filename(r, 4)
        assert (out_mh / name).read_bytes() == (out_sp / name).read_bytes()

# 3-D driver across two processes (round-3 parity): guarded run over a
# (2,1,2) volume mesh spanning the process boundary, a sharded 3-D
# checkpoint (per-process piece files, no host assembles the volume), and
# a cross-process sharded resume — dump byte-identical to single-process.
_WORKER_3D = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gol_tpu import compat as _compat
    _compat.set_cpu_device_count(2)
    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod
    pid = sys.argv[1]
    rc = cli3d.main([
        "2", "64", "5", "16", "1",
        "--mesh", "3d", "--mesh-shape", "2,1,2", "--engine", "bitpack",
        "--coordinator", sys.argv[2],
        "--num-processes", "2", "--process-id", pid,
        "--outdir", sys.argv[3],
        "--checkpoint-every", "3", "--checkpoint-dir", sys.argv[4],
        "--guard-every", "3",
    ])
    if rc == 0:
        rc = cli3d.main([
            "2", "64", "2", "16", "1",
            "--mesh", "3d", "--mesh-shape", "2,1,2", "--engine", "bitpack",
            "--outdir", sys.argv[5],
            "--resume", ckpt_mod.sharded_checkpoint3d_path(sys.argv[4], 3),
        ])
    sys.exit(rc)
    """
)


def test_two_process_cli3d_sharded_guard_and_resume(tmp_path):
    from gol_tpu import cli3d
    from gol_tpu.utils import checkpoint as ckpt_mod

    out_mh = tmp_path / "mh"
    out_rs = tmp_path / "rs"
    out_sp = tmp_path / "sp"
    ck = tmp_path / "ck"
    for d in (out_mh, out_rs, out_sp):
        d.mkdir()
    outs = _run_two_workers(_WORKER_3D, [str(out_mh), str(ck), str(out_rs)])
    assert "GUARD" in outs[0][1]  # coordinator printed the guard summary
    # The checkpoint is the sharded directory format with both processes'
    # piece files, globally stamped.
    ckdir = ckpt_mod.sharded_checkpoint3d_path(str(ck), 3)
    meta = ckpt_mod.load_sharded3d_meta(ckdir)
    assert sorted(set(int(p) for p in meta.procs)) == [0, 1]
    assert meta.fingerprint is not None

    rc = cli3d.main(
        ["2", "64", "5", "16", "1", "--engine", "bitpack",
         "--outdir", str(out_sp)]
    )
    assert rc == 0
    a = np.load(out_sp / "World3D_of_1.npy")
    np.testing.assert_array_equal(np.load(out_mh / "World3D_of_1.npy"), a)
    np.testing.assert_array_equal(np.load(out_rs / "World3D_of_1.npy"), a)


# Multi-host resume agreement (docs/RESILIENCE.md): after a 6-generation
# run with sharded checkpoints at gens 2/4/6, rank 1 corrupts its OWN
# piece of the newest snapshot, and both ranks --auto-resume with a
# total target of 12.  Each rank validates only the pieces it wrote, so
# rank 0 still trusts gen 6 — the min-generation agreement must drag
# both ranks back to gen 4 (no rank resumes ahead of another), and the
# resumed job's dumps must byte-match the unbroken 12-generation run.
_WORKER_AUTORESUME = textwrap.dedent(
    """
    import os
    import sys

    import numpy as np

    import jax
    jax.config.update("jax_platforms", "cpu")
    from gol_tpu import compat as _compat
    _compat.set_cpu_device_count(2)
    from gol_tpu import cli
    from gol_tpu.utils import checkpoint as ckpt_mod
    pid = sys.argv[1]
    ckdir, outdir, tmdir = sys.argv[3], sys.argv[4], sys.argv[5]
    rc = cli.main([
        "4", "8", "6", "16", "0",
        "--ranks", "4", "--mesh", "1d",
        "--coordinator", sys.argv[2],
        "--num-processes", "2", "--process-id", pid,
        "--checkpoint-every", "2", "--checkpoint-dir", ckdir,
    ])
    if rc == 0:
        if pid == "1":
            # Corrupt rank 1's own piece of the NEWEST snapshot (stored
            # fingerprints untouched, so only verification catches it).
            shards = os.path.join(
                ckpt_mod.sharded_checkpoint_path(ckdir, 6),
                "shards_00001.npz",
            )
            with np.load(shards) as data:
                arrays = {k: data[k].copy() for k in data.files}
            arrays["piece_0"][0, 0] ^= 1  # in-range flip
            np.savez_compressed(shards, **arrays)
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("corruption_injected")
        rc = cli.main([
            "4", "8", "12", "16", "1",
            "--ranks", "4", "--mesh", "1d",
            "--checkpoint-every", "2", "--checkpoint-dir", ckdir,
            "--auto-resume",
            "--outdir", outdir,
            "--telemetry", tmdir, "--run-id", "ar",
        ])
    sys.exit(rc)
    """
)


def test_two_process_auto_resume_min_generation_agreement(tmp_path):
    import json

    ck = tmp_path / "ck"
    out_mh = tmp_path / "mh"
    out_sp = tmp_path / "sp"
    tm = tmp_path / "tm"
    out_mh.mkdir()

    outs = _run_two_workers(
        _WORKER_AUTORESUME, [str(ck), str(out_mh), str(tm)]
    )
    # The coordinator logged the agreed fallback generation.
    assert "auto-resume: generation 4" in outs[0][1]

    # Unbroken single-process run of the same 12 generations.
    from gol_tpu import cli

    assert (
        cli.main(["4", "8", "12", "16", "1", "--ranks", "4",
                  "--outdir", str(out_sp)])
        == 0
    )
    for r in range(4):
        name = gol_io.rank_filename(r, 4)
        assert (out_mh / name).read_bytes() == (
            out_sp / name
        ).read_bytes(), f"rank {r} dump differs after agreed fallback"

    # Both ranks' telemetry recorded the same fallback resume decision.
    for rank in (0, 1):
        recs = [
            json.loads(ln) for ln in open(tm / f"ar.rank{rank}.jsonl")
        ]
        res = [rec for rec in recs if rec["event"] == "resume"]
        assert len(res) == 1, res
        assert res[0]["generation"] == 4 and res[0]["fallback"] is True


# Collective preemption (docs/RESILIENCE.md): SIGTERM is delivered to
# ONE worker only.  The chunk-boundary poll is an allgathered max, so
# BOTH ranks must preempt at the same boundary (a rank exiting alone
# would strand its peer in the next chunk's collectives), both exit 75
# with the sharded boundary snapshot on disk, and both then auto-resume
# to the total target — dumps byte-equal to the unbroken run.
_WORKER_PREEMPT = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gol_tpu import compat as _compat
    _compat.set_cpu_device_count(2)
    from gol_tpu import cli
    pid = sys.argv[1]
    ckdir, outdir = sys.argv[3], sys.argv[4]
    args = [
        "4", "16", "200", "16", "1",
        "--ranks", "4", "--mesh", "1d",
        "--checkpoint-every", "2", "--checkpoint-dir", ckdir,
        "--auto-resume", "--outdir", outdir,
    ]
    rc = cli.main(args + [
        "--coordinator", sys.argv[2],
        "--num-processes", "2", "--process-id", pid,
    ])
    print("FIRST_RC", rc, flush=True)
    if rc == 75:
        # Relaunch with identical argv (the supervisor contract): the
        # already-connected topology is reused, auto-resume completes
        # the remaining generations to the 200 target.
        rc = cli.main(args)
        sys.exit(rc)
    sys.exit(rc if rc else 99)  # 99: the SIGTERM raced the whole run
    """
)


def test_two_process_collective_preemption(tmp_path):
    import time as time_mod

    ck = tmp_path / "ck"
    out_mh = tmp_path / "mh"
    out_sp = tmp_path / "sp"
    out_mh.mkdir()

    coord = f"localhost:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_PREEMPT, str(i), coord,
             str(ck), str(out_mh)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            cwd=repo,
        )
        for i in range(2)
    ]
    try:
        # SIGTERM worker 0 ONLY, once its first sharded snapshot exists.
        deadline = time_mod.time() + 180
        while time_mod.time() < deadline:
            if ck.is_dir() and any(
                n.name.endswith(".gol.d") for n in ck.iterdir()
            ):
                break
            if procs[0].poll() is not None:
                break  # raced: worker finished before any signal
            time_mod.sleep(0.01)
        if procs[0].poll() is None:
            procs[0].send_signal(subprocess.signal.SIGTERM)
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out.decode(), err.decode()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
    # BOTH ranks took the cooperative exit — including rank 1, which
    # never received a signal (the allgathered flag preempted it).
    assert "FIRST_RC 75" in outs[0][1], outs[0][1]
    assert "FIRST_RC 75" in outs[1][1], outs[1][1]

    from gol_tpu import cli

    assert (
        cli.main(["4", "16", "200", "16", "1", "--ranks", "4",
                  "--outdir", str(out_sp)])
        == 0
    )
    for r in range(4):
        name = gol_io.rank_filename(r, 4)
        assert (out_mh / name).read_bytes() == (
            out_sp / name
        ).read_bytes(), f"rank {r} dump differs after preempt+resume"
