"""BROKEN fixture (never imported — parsed only, by spmdcheck teeth).

The classic SPMD divergence deadlock: a collective gated on
``jax.process_index()``.  Rank 0 enters the allgather and waits for
peers that already skipped the branch.  spmdcheck MUST flag both the
branch-gated site and the one shadowed by a rank-conditional early
return — if either goes green, the divergence check lost its witness.
"""

import jax

from gol_tpu.parallel import multihost


def save_manifest(generation: int) -> list:
    gathered = []
    if jax.process_index() == 0:
        # BUG: only rank 0 reaches the rendezvous.
        gathered = multihost.allgather_host_ints(generation)
    return gathered


def publish(generation: int) -> int:
    me = jax.process_index()
    if me != 0:
        return 0
    # BUG: every rank but 0 returned above; this barrier never forms.
    vals = multihost.allgather_host_ints(generation)
    return max(vals)
