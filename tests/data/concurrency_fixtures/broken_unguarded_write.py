"""BROKEN fixture (never imported — parsed only, by lockcheck teeth).

The pre-PR-16 serve-tier shape: a drive loop mutates request state
under the worker's lock, while an HTTP-handler-like reader thread
reads the same fields with no lock at all.  ``Worker.status`` is
shared by two thread entry points and mutated, so every lock-free
access is a guarded-field violation lockcheck MUST flag.
"""

import threading


class Worker:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.status = "queued"
        self.result = None

    def run_once(self) -> None:
        with self._lock:
            self.status = "running"
            self.result = {"ok": True}
            self.status = "done"


def drive(worker: Worker) -> None:
    while True:
        worker.run_once()


def handler(worker: Worker) -> dict:
    # BUG: terminal status can be observed before result is published,
    # and neither read holds worker._lock.
    if worker.status == "done":
        return worker.result
    return {"status": worker.status}


def start(worker: Worker) -> None:
    threading.Thread(
        target=drive, args=(worker,), name="drive", daemon=True
    ).start()
    threading.Thread(
        target=handler, args=(worker,), name="http", daemon=True
    ).start()
