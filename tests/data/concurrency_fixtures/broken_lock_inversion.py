"""BROKEN fixture (never imported — parsed only, by lockcheck teeth).

A textbook AB/BA lock inversion: the poller takes journal_lock then
stats_lock, the reporter takes stats_lock then journal_lock.  Each
order is individually fine; together they deadlock the moment both
threads hold their first lock.  lockcheck MUST report a lock-order
cycle here — if it stops doing so, the deadlock detector has lost its
witness (see gol_tpu/analysis/lockcheck.py TEETH).
"""

import threading

journal_lock = threading.Lock()
stats_lock = threading.Lock()

_journal = []
_stats = {"polls": 0}


def poller() -> None:
    while True:
        with journal_lock:
            _journal.append("poll")
            with stats_lock:
                _stats["polls"] += 1


def reporter() -> None:
    while True:
        with stats_lock:
            n = _stats["polls"]
            with journal_lock:
                _journal.append(f"report:{n}")


def start() -> None:
    threading.Thread(target=poller, name="poller", daemon=True).start()
    threading.Thread(target=reporter, name="reporter", daemon=True).start()
