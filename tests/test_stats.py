"""In-graph chunk statistics (``--stats`` / ``GolRuntime.stats``).

The acceptance pins of the stats subsystem:

- **evolution untouched**: stats on ⇒ final grid bit-equal to stats off,
  for every engine tier × mesh none/1d/2d the CPU backend dispatches
  (the stats wrapper calls the unmodified engine program);
- **values honest**: the emitted population equals an independent
  host-side (NumPy) recount of the final grid, and every field —
  births/deaths/changed/faces — matches a NumPy model of the chunk diff,
  identically for the dense and popcount (packed) reducers;
- **global on meshes**: sharded runs report the psummed world value,
  not a shard's (and the real 2-process test asserts both ranks emit
  the identical number);
- **memory introspection**: the dense tier's compiled argument+output
  bytes sit within 2× of ``roofline.xla_bytes_model`` (the byte-side
  twin of the verifier's FLOP gate);
- **mode hygiene**: stats mode excludes the guard, and the CLI requires
  a telemetry sink.

(The stats-off trace-identity pin lives in tests/test_telemetry.py —
the stats-off path does not pass through the stats module at all.)
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax

from gol_tpu.models import patterns
from gol_tpu.models.state import Geometry
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.runtime import GolRuntime

jax.config.update("jax_platforms", "cpu")


def _mesh(kind):
    if kind == "none":
        return None
    if kind == "1d":
        return mesh_mod.make_mesh_1d(4)
    return mesh_mod.make_mesh_2d((2, 2), devices=jax.devices()[:4])


def _np_chunk_stats(prev, new, band=1):
    """Independent NumPy model of one chunk's stats fields."""
    prev = np.asarray(prev, dtype=np.int64)
    new = np.asarray(new, dtype=np.int64)
    flips = prev ^ new
    return {
        "population": int(new.sum()),
        "births": int((flips & new).sum()),
        "deaths": int((flips & prev).sum()),
        "changed": int(flips.sum()),
        "face_top": int(new[:band].sum()),
        "face_bottom": int(new[-band:].sum()),
        "face_left": int(new[:, :band].sum()),
        "face_right": int(new[:, -band:].sum()),
    }


# -- evolution untouched: tier × mesh bit-equality ---------------------------


@pytest.mark.parametrize(
    "engine,mesh_kind",
    [
        ("dense", "none"),
        ("bitpack", "none"),
        ("pallas", "none"),
        ("pallas_bitpack", "none"),
        ("dense", "1d"),
        ("bitpack", "1d"),
        ("pallas_bitpack", "1d"),
        ("dense", "2d"),
        ("bitpack", "2d"),
    ],
)
def test_stats_on_final_grid_bit_equal(engine, mesh_kind):
    kw = dict(
        geometry=Geometry(size=64, num_ranks=1),
        engine=engine,
        mesh=_mesh(mesh_kind),
    )
    _, state_off = GolRuntime(**kw).run(pattern=4, iterations=8)
    rt_on = GolRuntime(**kw, stats=True)
    _, state_on = rt_on.run(pattern=4, iterations=8)
    np.testing.assert_array_equal(
        np.asarray(state_off.board), np.asarray(state_on.board)
    )
    # The emitted population is the whole world's, recounted on host.
    assert rt_on.last_stats, "stats mode produced no chunk stats"
    assert rt_on.last_stats[-1]["population"] == int(
        np.asarray(state_on.board, dtype=np.int64).sum()
    )


def test_stats_on_final_grid_bit_equal_pallas_2d():
    """The remaining tier×mesh cell: the sharded Pallas engine on a 2-D
    block mesh needs ≥ 2 packed words per shard, hence size 128."""
    kw = dict(
        geometry=Geometry(size=128, num_ranks=1),
        engine="pallas_bitpack",
        mesh=_mesh("2d"),
    )
    _, state_off = GolRuntime(**kw).run(pattern=6, iterations=8)
    rt_on = GolRuntime(**kw, stats=True)
    _, state_on = rt_on.run(pattern=6, iterations=8)
    np.testing.assert_array_equal(
        np.asarray(state_off.board), np.asarray(state_on.board)
    )
    assert rt_on.last_stats[-1]["population"] == int(
        np.asarray(state_on.board, dtype=np.int64).sum()
    )


@pytest.mark.parametrize(
    "kw",
    [
        dict(engine="dense", halo_mode="stale_t0"),
        dict(engine="bitpack", rule="B36/S23"),
    ],
)
def test_stats_on_special_modes_bit_equal(kw):
    geom = (
        Geometry(size=16, num_ranks=4)
        if kw.get("halo_mode") == "stale_t0"
        else Geometry(size=64, num_ranks=1)
    )
    _, state_off = GolRuntime(geometry=geom, **kw).run(
        pattern=1, iterations=6
    )
    rt_on = GolRuntime(geometry=geom, **kw, stats=True)
    _, state_on = rt_on.run(pattern=1, iterations=6)
    np.testing.assert_array_equal(
        np.asarray(state_off.board), np.asarray(state_on.board)
    )
    assert rt_on.last_stats[-1]["population"] == int(
        np.asarray(state_on.board, dtype=np.int64).sum()
    )


# -- values honest: every field vs the NumPy model ---------------------------


@pytest.mark.parametrize("engine", ["dense", "bitpack"])
@pytest.mark.parametrize("pattern", [4, 6])
def test_stats_fields_match_numpy_model(engine, pattern):
    """Single-chunk run: prev is the pattern-init board, so every field
    (births/deaths/changed/faces included) has an independent oracle —
    and dense vs popcount reducers must agree with it identically.
    Pattern 4 (wrap-spanning corner blinker) puts live cells in every
    boundary band; pattern 6 (r-pentomino) churns births/deaths."""
    geom = Geometry(size=64, num_ranks=1)
    rt = GolRuntime(geometry=geom, engine=engine, stats=True)
    _, state = rt.run(pattern=pattern, iterations=5)
    board0 = patterns.init_global(pattern, 64, 1)
    expected = _np_chunk_stats(board0, np.asarray(state.board))
    (chunk_stats,) = rt.last_stats
    got = {k: chunk_stats[k] for k in expected}
    assert got == expected


def test_stats_global_on_mesh_matches_numpy_model():
    """Sharded run (2-D mesh): the psummed values are the *global*
    board's, identical to an unsharded NumPy recount — including the
    face bands that live on boundary shards only."""
    geom = Geometry(size=64, num_ranks=1)
    rt = GolRuntime(geometry=geom, engine="bitpack", mesh=_mesh("2d"),
                    stats=True)
    _, state = rt.run(pattern=4, iterations=5)
    board0 = patterns.init_global(4, 64, 1)
    expected = _np_chunk_stats(board0, np.asarray(state.board))
    (chunk_stats,) = rt.last_stats
    assert {k: chunk_stats[k] for k in expected} == expected


def test_stats_band_follows_halo_depth():
    """The face bands are ``halo_depth`` deep — the cells the next
    exchange ships."""
    geom = Geometry(size=64, num_ranks=1)
    rt = GolRuntime(
        geometry=geom, engine="dense", mesh=_mesh("1d"), halo_depth=2,
        stats=True,
    )
    _, state = rt.run(pattern=4, iterations=4)
    board0 = patterns.init_global(4, 64, 1)
    expected = _np_chunk_stats(board0, np.asarray(state.board), band=2)
    (chunk_stats,) = rt.last_stats
    assert {k: chunk_stats[k] for k in expected} == expected


def test_split_accumulator_exact_above_16_bits():
    """Populations past 2¹⁶ must survive the uint32 [hi, lo] pair —
    an all-ones 512×512 board is 262144 > 2¹⁶ live cells."""
    from gol_tpu.ops import stats as ops_stats

    board = np.ones((512, 512), np.uint8)
    dev = jax.device_put(board)
    got = ops_stats.stats_values(
        jax.jit(lambda p, n: ops_stats.dense_chunk_stats(p, n, 1))(dev, dev)
    )
    assert got["population"] == 512 * 512
    assert got["changed"] == 0
    got_packed = ops_stats.stats_values(
        jax.jit(lambda p, n: ops_stats.packed_chunk_stats(p, n, 1))(dev, dev)
    )
    assert got_packed == got


# -- telemetry emission ------------------------------------------------------


def test_stats_events_in_stream_and_summarize(tmp_path, capsys):
    from gol_tpu.telemetry import summarize as summ_mod

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        checkpoint_every=3,
        checkpoint_dir=str(tmp_path / "ck"),
        telemetry_dir=str(tmp_path / "t"),
        run_id="st",
        stats=True,
    )
    rt.run(pattern=4, iterations=8)
    recs = [json.loads(ln) for ln in open(tmp_path / "t" / "st.rank0.jsonl")]
    stats = [r for r in recs if r["event"] == "stats"]
    # One stats record per chunk, matching the schedule and last_stats.
    assert [s["take"] for s in stats] == [3, 3, 2]
    assert [s["generation"] for s in stats] == [3, 6, 8]
    assert [s["population"] for s in stats] == [
        s["population"] for s in rt.last_stats
    ]
    assert all(
        set(s["faces"]) == {"top", "bottom", "left", "right"} for s in stats
    )
    # compile events carry the memory block (CPU exposes memory_analysis).
    compiles = [r for r in recs if r["event"] == "compile"]
    assert all("memory" in c for c in compiles)
    assert all(c["memory"]["argument_bytes"] > 0 for c in compiles)
    # summarize renders the stats and memory tables and exits 0.
    assert summ_mod.main(["summarize", str(tmp_path / "t")]) == 0
    out = capsys.readouterr().out
    assert "stats     gen" in out
    assert "memory: chunk" in out


# -- memory introspection vs the roofline byte model -------------------------


def test_dense_memory_analysis_within_byte_model():
    from gol_tpu.telemetry import stats as stats_mod
    from gol_tpu.utils import roofline

    rt = GolRuntime(geometry=Geometry(size=64, num_ranks=1), engine="dense")
    fn, dynamic, static = rt._evolve_fn(8)
    spec = jax.ShapeDtypeStruct((64, 64), np.uint8)
    compiled = fn.lower(spec, *dynamic, *static).compile()
    mem = stats_mod.compiled_memory(compiled)
    assert mem is not None, "CPU backend stopped exposing memory_analysis"
    measured = mem["argument_bytes"] + mem["output_bytes"]
    model = roofline.xla_bytes_model("dense", 64 * 64)
    assert model / 2 <= measured <= model * 2, (
        f"compiled I/O bytes {measured} vs byte model {model}"
    )


# -- mode hygiene ------------------------------------------------------------


def test_guard_rejects_stats_runtime():
    from gol_tpu.utils import guard as guard_mod

    rt = GolRuntime(geometry=Geometry(size=64, num_ranks=1), stats=True)
    with pytest.raises(ValueError, match="unguarded"):
        guard_mod.run_guarded(
            rt, pattern=4, iterations=8,
            config=guard_mod.GuardConfig(check_every=4),
        )


def test_cli_stats_flag_validation(tmp_path, capsys):
    from gol_tpu import cli

    # --stats without --telemetry: clean error, reference exit status.
    assert cli.main(["0", "64", "8", "512", "0", "--stats"]) == 255
    assert "--telemetry" in capsys.readouterr().out
    # --stats with the guard: clean error.
    assert (
        cli.main(
            ["0", "64", "8", "512", "0", "--stats", "--telemetry",
             str(tmp_path / "t"), "--guard-every", "4"]
        )
        == 255
    )
    assert "unguarded" in capsys.readouterr().out


def test_cli_stats_end_to_end(tmp_path):
    from gol_tpu import cli

    d = tmp_path / "t"
    rc = cli.main(
        ["0", "64", "8", "512", "0", "--telemetry", str(d),
         "--run-id", "clistats", "--stats"]
    )
    assert rc == 0
    recs = [json.loads(ln) for ln in open(d / "clistats.rank0.jsonl")]
    assert sum(1 for r in recs if r["event"] == "stats") == 1


def test_cli3d_stats_end_to_end(tmp_path):
    from gol_tpu import cli3d

    d = tmp_path / "t3"
    rc = cli3d.main(
        ["2", "32", "4", "16", "0", "--engine", "bitpack",
         "--checkpoint-every", "2",
         "--checkpoint-dir", str(tmp_path / "ck3"),
         "--telemetry", str(d), "--run-id", "v3s", "--stats"]
    )
    assert rc == 0
    recs = [json.loads(ln) for ln in open(d / "v3s.rank0.jsonl")]
    stats = [r for r in recs if r["event"] == "stats"]
    assert [s["generation"] for s in stats] == [2, 4]
    # 3-D volumes report the scalar quartet; no face bands.
    assert all(s["faces"] == {} for s in stats)
    assert all(
        s["births"] + s["deaths"] == s["changed"] for s in stats
    )
    # Population of the final volume matches an independent recount.
    from gol_tpu.cli3d import init_volume
    from tests import oracle

    expected = oracle.run_torus3d(init_volume(2, 32), 4)
    assert stats[-1]["population"] == int(expected.sum())


# -- real 2-process psum (the test_multihost.py harness) ---------------------

_WORKER_STATS = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from gol_tpu import compat as _compat
_compat.set_cpu_device_count(2)
from gol_tpu import cli
pid = sys.argv[1]
sys.exit(cli.main([
    "4", "8", "4", "16", "0",
    "--ranks", "4", "--mesh", "1d",
    "--coordinator", sys.argv[2],
    "--num-processes", "2", "--process-id", pid,
    "--checkpoint-every", "2", "--checkpoint-dir", sys.argv[4],
    "--telemetry", sys.argv[3], "--run-id", "mhs", "--stats",
]))
"""


def test_two_process_stats_psum_agree(tmp_path):
    """Both ranks of a real 2-process (gloo) run emit the *same* global
    population via psum — and it matches the single-process run."""
    from tests.test_multihost import _run_two_workers

    tdir = tmp_path / "mhs"
    _run_two_workers(_WORKER_STATS, [str(tdir), str(tmp_path / "mhck")])

    def stats_of(rank):
        recs = [
            json.loads(ln) for ln in open(tdir / f"mhs.rank{rank}.jsonl")
        ]
        return [r for r in recs if r["event"] == "stats"]

    s0, s1 = stats_of(0), stats_of(1)
    assert len(s0) == len(s1) == 2  # chunks of 2 + 2 generations
    assert [(s["generation"], s["population"], s["changed"]) for s in s0] \
        == [(s["generation"], s["population"], s["changed"]) for s in s1]

    # Single-process oracle for the same world.
    rt = GolRuntime(
        geometry=Geometry(size=8, num_ranks=4),
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "spck"),
        stats=True,
    )
    rt.run(pattern=4, iterations=4)
    assert [s["population"] for s in s0] == [
        s["population"] for s in rt.last_stats
    ]
