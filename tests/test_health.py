"""The health plane (gol_tpu/resilience/health.py).

Watchdog behavior (baseline fit, straggler exclusion, the min-wall
floor), device loss/restore verdicts off the fault plane (including the
last-device guard and restore scheduling), and verdict emission into
the v11 telemetry stream / metrics registry.
"""

from __future__ import annotations

import json

import pytest

from gol_tpu.resilience import faults as faults_mod
from gol_tpu.resilience.health import KINDS, HealthMonitor, Verdict


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    faults_mod.clear()
    yield
    faults_mod.clear()


def _arm(*specs):
    faults_mod.install(faults_mod.FaultPlan.loads(json.dumps(list(specs))))


# -- construction -------------------------------------------------------------


def test_constructor_validates():
    with pytest.raises(ValueError):
        HealthMonitor(0)
    with pytest.raises(ValueError):
        HealthMonitor(4, straggler_factor=1.0)
    assert HealthMonitor(4).alive == [0, 1, 2, 3]


# -- the straggler watchdog ---------------------------------------------------


def test_baseline_needs_min_samples_then_fits_median():
    mon = HealthMonitor(4, min_samples=3)
    mon.heartbeat(2, 0.10)
    mon.heartbeat(4, 0.20)
    assert mon.baseline() is None
    mon.heartbeat(6, 0.30)
    assert mon.baseline() == pytest.approx(0.20)


def test_straggler_flagged_and_excluded_from_window():
    mon = HealthMonitor(4, straggler_factor=4.0, min_samples=3)
    for g, w in ((2, 0.10), (4, 0.10), (6, 0.10)):
        assert mon.heartbeat(g, w) == []
    (v,) = mon.heartbeat(8, 1.0, rank=2)
    assert v.kind == "straggler" and v.rank == 2
    assert v.wall_s == pytest.approx(1.0)
    assert v.baseline_s == pytest.approx(0.10)
    # the slow wall did NOT join the window: the baseline cannot be
    # dragged up by the straggler it is supposed to catch
    assert mon.baseline() == pytest.approx(0.10)
    assert mon.heartbeat(10, 1.0) and mon.baseline() == pytest.approx(0.10)


def test_min_wall_floor_suppresses_jitter_verdicts():
    mon = HealthMonitor(4, min_wall_s=0.010, min_samples=3)
    for g in (2, 4, 6):
        mon.heartbeat(g, 0.001)
    # 8x the baseline but under the floor: sub-10ms walls jitter by
    # whole multiples of themselves, so no verdict
    assert mon.heartbeat(8, 0.008) == []


def test_rank_slowdown_inflates_the_reported_wall():
    _arm({"site": "rank.slowdown", "at": 8, "delay_s": 30.0})
    mon = HealthMonitor(4, min_samples=3)
    for g in (2, 4, 6):
        mon.heartbeat(g, 0.05)
    (v,) = mon.heartbeat(8, 0.05)
    assert v.kind == "straggler"
    assert v.wall_s == pytest.approx(30.05)


# -- device loss / restore ----------------------------------------------------


def test_loss_then_scheduled_restore():
    _arm({"site": "device.loss", "at": 4, "device": 1, "restore_after": 6})
    mon = HealthMonitor(4)
    assert mon.poll(2) == []
    (v,) = mon.poll(4)
    assert (v.kind, v.device, v.alive) == ("device_loss", 1, 3)
    assert mon.alive == [0, 2, 3]
    assert mon.poll(8) == []  # restore due at 10, not yet
    (r,) = mon.poll(10)
    assert (r.kind, r.device, r.alive) == ("device_restore", 1, 4)
    assert mon.alive == [0, 1, 2, 3]


def test_last_device_cannot_be_shed():
    _arm(
        {"site": "device.loss", "at": 2, "device": 0},
        {"site": "device.loss", "at": 4, "device": 1},
    )
    mon = HealthMonitor(2)
    assert [v.kind for v in mon.poll(2)] == ["device_loss"]
    # losing device 1 would leave nothing to reshard onto: refused
    assert mon.poll(4) == []
    assert mon.alive == [1]


def test_losing_an_already_dead_device_is_a_noop():
    _arm(
        {"site": "device.loss", "at": 2, "device": 1},
        {"site": "device.loss", "at": 4, "device": 1},
    )
    mon = HealthMonitor(4)
    assert len(mon.poll(2)) == 1
    assert mon.poll(4) == []
    assert mon.alive == [0, 2, 3]


# -- emission -----------------------------------------------------------------


class _Registry:
    def __init__(self):
        self.records = []

    def observe(self, rec):
        self.records.append(rec)


def test_verdicts_reach_the_registry_when_no_event_log():
    _arm({"site": "device.loss", "at": 4, "device": 2})
    reg = _Registry()
    mon = HealthMonitor(4, registry=reg, min_samples=1)
    mon.poll(4)
    mon.heartbeat(6, 0.05)
    mon.heartbeat(8, 5.0)
    kinds = [r["verdict"] for r in reg.records]
    assert kinds == ["device_loss", "straggler"]
    assert all(r["event"] == "health" for r in reg.records)
    assert reg.records[0]["device"] == 2
    assert reg.records[0]["alive"] == 3


def test_verdicts_stamp_v11_health_events(tmp_path):
    from gol_tpu import telemetry

    _arm({"site": "device.loss", "at": 4, "device": 1, "restore_after": 2})
    with telemetry.EventLog(
        str(tmp_path), run_id="health", process_index=0
    ) as ev:
        ev.run_header({"driver": "test"})
        mon = HealthMonitor(4, events=ev)
        mon.poll(4)
        mon.poll(6)
        path = ev.path
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 11
    health = [r for r in recs if r["event"] == "health"]
    assert [r["verdict"] for r in health] == ["device_loss", "device_restore"]
    assert health[0]["generation"] == 4 and health[0]["device"] == 1


def test_verdict_event_payload_shape():
    v = Verdict("straggler", 10, rank=3, wall_s=1.23456789, baseline_s=0.1,
                alive=4)
    ev = v.to_event()
    assert ev["verdict"] == "straggler" and ev["rank"] == 3
    assert ev["wall_s"] == pytest.approx(1.234568)
    assert "device" not in ev  # no device for a straggler
    assert set(KINDS) == {"device_loss", "device_restore", "straggler"}
