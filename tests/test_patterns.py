"""Exact cell placement for every init pattern, incl. buggy-effective ones."""

import numpy as np
import pytest

from gol_tpu.models import patterns


def test_pattern0_all_zeros():
    b = patterns.init_global(0, 16, 3)
    assert b.shape == (48, 16) and b.dtype == np.uint8
    assert b.sum() == 0


def test_pattern1_all_ones():
    b = patterns.init_global(1, 16, 2)
    assert b.shape == (32, 16)
    assert (b == 1).all()


def test_pattern2_last_row_cols_127_136_every_rank():
    """Effective behavior of gol-with-cuda.cu:108-114 on square worlds:
    10 live cells on each rank's LAST local row, columns 127-136 (the
    'middle' in the name is a misnomer — bug B3)."""
    s, r = 140, 3
    b = patterns.init_global(2, s, r)
    expected = np.zeros((r * s, s), np.uint8)
    for rank in range(r):
        expected[rank * s + s - 1, 127:137] = 1
    np.testing.assert_array_equal(b, expected)
    assert b.sum() == 10 * r


def test_pattern2_small_world_rejected():
    """Bug B4 (OOB heap write when size < 137) becomes a clear error."""
    with pytest.raises(ValueError, match="137"):
        patterns.init_local(2, 136, 0, 1)
    patterns.init_local(2, 137, 0, 1)  # exactly at the bound: fine


def test_pattern3_global_corners():
    s, r = 8, 4
    b = patterns.init_global(3, s, r)
    expected = np.zeros((r * s, s), np.uint8)
    expected[0, 0] = expected[0, s - 1] = 1
    expected[r * s - 1, 0] = expected[r * s - 1, s - 1] = 1
    np.testing.assert_array_equal(b, expected)


def test_pattern3_single_rank_top_corners_only():
    """With numRank==1 the reference's `else if` (gol-with-cuda.cu:139) never
    fires: only the TOP corners are set."""
    b = patterns.init_global(3, 8, 1)
    assert b.sum() == 2
    assert b[0, 0] == 1 and b[0, 7] == 1


def test_pattern4_spinner_rank0_only():
    s, r = 8, 3
    b = patterns.init_global(4, s, r)
    assert b.sum() == 3
    assert b[0, 0] == 1 and b[0, 1] == 1 and b[0, s - 1] == 1


def test_unknown_pattern_rejected():
    # 10 is the first unassigned id (8/9 became the sparse-zoo seeds).
    with pytest.raises(ValueError, match="not been implemented"):
        patterns.init_local(10, 8, 0, 1)


def test_init_local_stacks_to_global():
    for pat in (0, 1, 3, 4):
        g = patterns.init_global(pat, 8, 4)
        for rank in range(4):
            np.testing.assert_array_equal(
                g[rank * 8 : (rank + 1) * 8],
                patterns.init_local(pat, 8, rank, 4),
            )


# -- capability-addition object patterns (ids 5-7) ---------------------------


def test_glider_cells_and_translation():
    from tests import oracle

    b = patterns.init_global(5, 16, 1)
    assert b.sum() == 5
    # A glider translates (+1, +1) every 4 generations on the torus.
    evolved = oracle.run_torus(b, 4)
    np.testing.assert_array_equal(evolved, np.roll(b, (1, 1), axis=(0, 1)))


def test_glider_full_torus_transit():
    """Soak probe: after 4*size generations the glider is back exactly —
    one full diagonal transit through both wraps."""
    from tests import oracle

    size = 16
    b = patterns.init_global(5, size, 1)
    np.testing.assert_array_equal(oracle.run_torus(b, 4 * size), b)


def test_glider_transit_on_engines():
    """The same transit through every engine (dense jit + packed + sharded)."""
    import jax.numpy as jnp

    from gol_tpu.ops import bitlife, stencil
    from gol_tpu.parallel import mesh as mesh_mod, sharded

    size = 32  # width must pack into words for the bit-packed engine
    b = patterns.init_global(5, size, 1)
    steps = 4 * size
    got = np.asarray(stencil.run(jnp.asarray(b), steps))
    np.testing.assert_array_equal(got, b)
    got = np.asarray(bitlife.evolve_dense_io(jnp.asarray(b), steps))
    np.testing.assert_array_equal(got, b)
    mesh = mesh_mod.make_mesh_1d(4)
    got = np.asarray(sharded.evolve_sharded(jnp.asarray(b), steps, mesh))
    np.testing.assert_array_equal(got, b)


def test_r_pentomino_centered_across_ranks():
    b = patterns.init_global(6, 8, 2)  # 16x8 world; center spans ranks
    assert b.sum() == 5
    rows, cols = np.nonzero(b)
    assert rows.min() == 7 and rows.max() == 9  # crosses the rank-0/1 seam
    # Stacking init_local per rank must reproduce the global placement.
    for rank in range(2):
        np.testing.assert_array_equal(
            b[rank * 8 : (rank + 1) * 8], patterns.init_local(6, 8, rank, 2)
        )


def test_gosper_gun_emission_rate():
    from tests import oracle

    b = patterns.init_global(7, 48, 1)
    assert b.sum() == 36
    assert oracle.run_torus(b, 30).sum() == 36 + 5  # one glider emitted
    assert oracle.run_torus(b, 60).sum() == 36 + 10  # two


def test_object_pattern_size_validation():
    with pytest.raises(ValueError, match="worldSize"):
        patterns.init_local(7, 32, 0, 1)
    with pytest.raises(ValueError, match="worldSize"):
        patterns.init_local(5, 4, 0, 1)
