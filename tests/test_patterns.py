"""Exact cell placement for every init pattern, incl. buggy-effective ones."""

import numpy as np
import pytest

from gol_tpu.models import patterns


def test_pattern0_all_zeros():
    b = patterns.init_global(0, 16, 3)
    assert b.shape == (48, 16) and b.dtype == np.uint8
    assert b.sum() == 0


def test_pattern1_all_ones():
    b = patterns.init_global(1, 16, 2)
    assert b.shape == (32, 16)
    assert (b == 1).all()


def test_pattern2_last_row_cols_127_136_every_rank():
    """Effective behavior of gol-with-cuda.cu:108-114 on square worlds:
    10 live cells on each rank's LAST local row, columns 127-136 (the
    'middle' in the name is a misnomer — bug B3)."""
    s, r = 140, 3
    b = patterns.init_global(2, s, r)
    expected = np.zeros((r * s, s), np.uint8)
    for rank in range(r):
        expected[rank * s + s - 1, 127:137] = 1
    np.testing.assert_array_equal(b, expected)
    assert b.sum() == 10 * r


def test_pattern2_small_world_rejected():
    """Bug B4 (OOB heap write when size < 137) becomes a clear error."""
    with pytest.raises(ValueError, match="137"):
        patterns.init_local(2, 136, 0, 1)
    patterns.init_local(2, 137, 0, 1)  # exactly at the bound: fine


def test_pattern3_global_corners():
    s, r = 8, 4
    b = patterns.init_global(3, s, r)
    expected = np.zeros((r * s, s), np.uint8)
    expected[0, 0] = expected[0, s - 1] = 1
    expected[r * s - 1, 0] = expected[r * s - 1, s - 1] = 1
    np.testing.assert_array_equal(b, expected)


def test_pattern3_single_rank_top_corners_only():
    """With numRank==1 the reference's `else if` (gol-with-cuda.cu:139) never
    fires: only the TOP corners are set."""
    b = patterns.init_global(3, 8, 1)
    assert b.sum() == 2
    assert b[0, 0] == 1 and b[0, 7] == 1


def test_pattern4_spinner_rank0_only():
    s, r = 8, 3
    b = patterns.init_global(4, s, r)
    assert b.sum() == 3
    assert b[0, 0] == 1 and b[0, 1] == 1 and b[0, s - 1] == 1


def test_unknown_pattern_rejected():
    with pytest.raises(ValueError, match="not been implemented"):
        patterns.init_local(5, 8, 0, 1)


def test_init_local_stacks_to_global():
    for pat in (0, 1, 3, 4):
        g = patterns.init_global(pat, 8, 4)
        for rank in range(4):
            np.testing.assert_array_equal(
                g[rank * 8 : (rank + 1) * 8],
                patterns.init_local(pat, 8, rank, 4),
            )
