"""Single-device stencil vs. the independent NumPy oracle, plus known seeds."""

import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.ops import stencil

from tests import oracle


random_board = oracle.random_board


@pytest.mark.parametrize("shape", [(8, 8), (16, 32), (33, 17), (1, 8), (64, 64)])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_step_matches_oracle(shape, seed):
    board = random_board(*shape, seed)
    got = np.asarray(stencil.step(jnp.asarray(board)))
    np.testing.assert_array_equal(got, oracle.step_torus(board))


@pytest.mark.parametrize("shape", [(16, 16), (24, 40)])
def test_reduce_window_variant_matches_roll(shape):
    board = random_board(*shape, 7)
    a = np.asarray(stencil.step(jnp.asarray(board)))
    b = np.asarray(stencil.step_reduce_window(jnp.asarray(board)))
    np.testing.assert_array_equal(a, b)


def test_run_many_steps_matches_oracle():
    board = random_board(32, 32, 3)
    got = np.asarray(stencil.run(jnp.asarray(board), 10))
    np.testing.assert_array_equal(got, oracle.run_torus(board, 10))


def test_wrap_both_axes():
    """A glider crossing each edge must re-enter on the opposite side."""
    board = np.zeros((8, 8), np.uint8)
    # Glider in the top-left corner, heading up-left so it wraps both axes.
    board[0, 0] = board[0, 1] = board[0, 2] = 1
    board[1, 0] = 1
    board[2, 1] = 1
    out = board
    for _ in range(4 * 8):  # gliders translate by (±1,±1) every 4 steps
        out = oracle.step_torus(out)
    got = np.asarray(stencil.run(jnp.asarray(board), 4 * 8))
    np.testing.assert_array_equal(got, out)
    assert got.sum() == 5  # still a glider


def test_blinker_oscillates_across_wrap():
    """Pattern 4's wrap-spanning blinker (gol-with-cuda.cu:161-165) has
    period 2 under correct torus semantics."""
    board = np.zeros((8, 8), np.uint8)
    board[0, 0] = board[0, 1] = board[0, 7] = 1  # horizontal, spans x-wrap
    b1 = np.asarray(stencil.step(jnp.asarray(board)))
    b2 = np.asarray(stencil.step(jnp.asarray(b1)))
    assert b1.sum() == 3 and not np.array_equal(b1, board)  # vertical phase
    np.testing.assert_array_equal(b2, board)  # back to horizontal


def test_corner_cells_die():
    """Pattern 3's isolated corner cells die of underpopulation in one step
    (rule at gol-with-cuda.cu:240-241) — but note on a small torus the four
    corners are mutual neighbors; use a big enough board to isolate them."""
    board = np.zeros((16, 16), np.uint8)
    board[0, 0] = board[0, 15] = board[15, 0] = board[15, 15] = 1
    # On the torus the 4 global corners are pairwise adjacent (each has 3
    # neighbors!) — they form a 2×2 block across the wrap, which is a still
    # life. This is real torus semantics, worth pinning down:
    out = np.asarray(stencil.step(jnp.asarray(board)))
    np.testing.assert_array_equal(out, board)  # still life across the wrap
    # A genuinely isolated cell dies:
    board2 = np.zeros((16, 16), np.uint8)
    board2[7, 7] = 1
    out2 = np.asarray(stencil.step(jnp.asarray(board2)))
    assert out2.sum() == 0


def test_step_halo_rows_equals_torus_when_self_wrapped():
    board = random_board(12, 12, 11)
    got = np.asarray(
        stencil.step_halo_rows(
            jnp.asarray(board), jnp.asarray(board[-1]), jnp.asarray(board[0])
        )
    )
    np.testing.assert_array_equal(got, oracle.step_torus(board))


def test_step_halo_full_equals_torus():
    board = random_board(10, 14, 13)
    ext = np.pad(board, 1, mode="wrap")
    got = np.asarray(stencil.step_halo_full(jnp.asarray(ext)))
    np.testing.assert_array_equal(got, oracle.step_torus(board))


def test_reference_semantics_single_rank():
    """Compat path reproduces the stale-halo (B1) single-rank evolution."""
    board = random_board(16, 16, 5)
    got = np.asarray(stencil.run_reference_semantics(jnp.asarray(board), 8))
    expected = oracle.simulate_reference(board, num_ranks=1, steps=8)
    np.testing.assert_array_equal(got, expected)
    # And it genuinely diverges from correct torus semantics on this seed:
    assert not np.array_equal(expected, oracle.run_torus(board, 8))
