"""Schema v14 (serving-fleet events) + v1–v13 compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..13}.py.
Here:

- the v14 additions round-trip: a ``fleet`` record per front-tier
  decision — ``route`` / ``epoch`` / ``handoff`` / ``replica`` /
  ``drain`` (docs/OBSERVABILITY.md, docs/SERVING.md "The fleet");
- the committed v14 fixture is a REAL fleet session: two supervised
  replicas, three routed requests, a ``kill -9`` of the owner, the
  journaled handoff of all three intents to the survivor, the restore
  verdict, and the graceful drain;
- **back-compat**: all THIRTEEN committed fixtures — PR 2 (v1) through
  PR 19 (v14) — still load, merge, and render in one ``summarize``
  pass (exit 0) with the fleet line;
- a stream from a FUTURE schema fails loudly ("newer than this reader
  supports", exit 2) instead of KeyError'ing deep in a consumer;
- the ``gol_fleet_*`` metrics are fed from the same records the JSONL
  carries, and stay absent until a fleet event is observed.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax
import pytest

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod
from gol_tpu.telemetry.metrics import MetricsRegistry

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
    8: DATA / "telemetry_v8" / "pr10run.rank0.jsonl",
    9: DATA / "telemetry_v9" / "pr12run.rank0.jsonl",
    11: DATA / "telemetry_v11" / "pr14run.rank0.jsonl",
    12: DATA / "telemetry_v12" / "pr17run.rank0.jsonl",
    13: DATA / "telemetry_v13" / "pr18run.rank0.jsonl",
    14: DATA / "telemetry_v14" / "pr19run.rank0.jsonl",
}


def _v14_stream(directory, run_id="v14"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header({"driver": "fleet", "replicas": 2})
        ev.fleet_event(
            "epoch", epoch=1, members=["r0", "r1"], reason="boot"
        )
        ev.fleet_event(
            "route", request_id="x0", bucket="64x64:bitpack",
            replica="r0", epoch=1,
        )
        ev.fleet_event(
            "replica", verdict="replica_dead", replica="r0", alive=1,
            tick=7,
        )
        ev.fleet_event(
            "handoff", request_id="x0", src="r0", dst="r1", epoch=2,
        )
        ev.fleet_event("drain", epoch=2)
        return ev.path


def test_v14_roundtrip(tmp_path):
    path = _v14_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 14
    assert set(telemetry.SUPPORTED_SCHEMAS) >= set(range(1, 15))
    fleets = [r for r in recs if r["event"] == "fleet"]
    assert [f["action"] for f in fleets] == [
        "epoch", "route", "replica", "handoff", "drain",
    ]
    assert fleets[1]["bucket"] == "64x64:bitpack"
    assert fleets[2]["verdict"] == "replica_dead"
    assert fleets[3]["src"] == "r0" and fleets[3]["dst"] == "r1"


def test_fleet_event_validates_required_fields(tmp_path):
    with telemetry.EventLog(
        str(tmp_path), run_id="bad", process_index=0
    ) as ev:
        ev.run_header({})
        with pytest.raises(telemetry.SchemaError, match="fleet"):
            ev.emit("fleet", epoch=1)  # no action


def test_committed_fixture_schemas():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v14_fixture_is_a_real_fleet_session():
    """The committed stream came from a real 2-replica fleet: three
    requests routed to one replica, the replica SIGKILLed, every open
    intent handed to the survivor under a bumped epoch, the restore
    verdict once the supervisor relaunched it, then a drain."""
    recs = [json.loads(ln) for ln in FIXTURES[14].open()]
    assert recs[0]["config"]["driver"] == "fleet"
    fleets = [r for r in recs if r["event"] == "fleet"]
    by = {}
    for f in fleets:
        by.setdefault(f["action"], []).append(f)
    # Boot, dead, restore: three epoch bumps, strictly increasing.
    epochs = [e["epoch"] for e in by["epoch"]]
    assert epochs == sorted(epochs) and len(set(epochs)) == len(epochs)
    assert by["epoch"][0]["reason"] == "boot"
    reasons = [e["reason"] for e in by["epoch"]]
    assert any(r.startswith("replica_dead:") for r in reasons)
    assert any(r.startswith("replica_restore:") for r in reasons)
    # Every route names its bucket, replica, and the epoch it was
    # pinned under; every routed id was handed off (the kill caught
    # all three open).
    routed = {r["request_id"] for r in by["route"]}
    assert all(r["bucket"] and r["replica"] for r in by["route"])
    handed = {h["request_id"] for h in by["handoff"]}
    assert routed == handed and len(routed) == 3
    victim = by["route"][0]["replica"]
    assert all(h["src"] == victim for h in by["handoff"])
    assert all(h["dst"] != victim for h in by["handoff"])
    # The handoff epoch is the dead-bump epoch — later than the route's.
    assert all(
        h["epoch"] > by["route"][0]["epoch"] for h in by["handoff"]
    )
    verdicts = [v["verdict"] for v in by["replica"]]
    assert verdicts == ["replica_dead", "replica_restore"]
    assert by["replica"][0]["alive"] < by["replica"][1]["alive"]
    assert by["drain"][-1] is fleets[-1]


def test_v14_fixture_summarize_renders_fleet_line(capsys):
    assert summ_mod.main(
        ["summarize", str(FIXTURES[14].parent)]
    ) == 0
    out = capsys.readouterr().out
    assert "fleet:" in out
    assert "3 handoff" in out and "3 route" in out
    assert "routing epoch now 3" in out


def test_v1_to_v14_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v14_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "pr10run", "pr12run", "pr14run", "pr17run",
        "pr18run", "pr19run", "v14",
    ):
        assert run_id in out
    assert "fleet:" in out


def test_future_schema_fails_loudly_not_keyerror(tmp_path, capsys):
    future = telemetry.SCHEMA_VERSION + 1
    (tmp_path / "fut.rank0.jsonl").write_text(
        json.dumps(
            {
                "event": "run_header", "t": 0.0, "schema": future,
                "run_id": "fut", "process_index": 0, "process_count": 1,
                "config": {},
            }
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert f"schema v{future} is newer than this reader supports" in err
    assert f"max v{telemetry.SCHEMA_VERSION}" in err


def test_fleet_metrics_from_fixture():
    """The gol_fleet_* family is fed from the SAME records the JSONL
    carries — and stays absent until a fleet event is observed."""
    reg = MetricsRegistry()
    assert "gol_fleet" not in reg.render()
    for ln in FIXTURES[14].open():
        reg.observe(json.loads(ln))
    text = reg.render()
    assert "gol_fleet_epoch 3" in text
    assert "gol_fleet_replicas_alive 2" in text
    assert "gol_fleet_routed_total 3" in text
    assert "gol_fleet_handoffs_total 3" in text
    assert "gol_fleet_replica_dead_total 1" in text
    assert "gol_fleet_replica_restore_total 1" in text
