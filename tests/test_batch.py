"""Batched multi-world engine (gol_tpu/batch, docs/BATCHING.md).

The bit-exactness contract under test everywhere: a batched run of B
worlds is bit-identical **per world** to B sequential single-world runs
of the existing engines — exact and padded+masked buckets, every tier,
world-axis sharding on and off — plus the serving machinery around it:
schema-v4 telemetry, batched checkpoints on the PR 4 validated-resume
path, cooperative preemption, the persistent compilation cache, the CLI
surface, and the trace-identity pin (building batched programs leaves
every single-world jaxpr byte-identical).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_tpu import resilience
from gol_tpu.batch import (
    GolBatchRuntime,
    bucket_shape,
    bucketize,
    cache_entries,
    compiled_batch_evolver,
    make_batch_mesh,
    resolve_bucket_engine,
)
from gol_tpu.batch.runtime import Bucket
from gol_tpu.models.state import Geometry
from gol_tpu.ops import stencil
from gol_tpu.runtime import GolRuntime
from gol_tpu.utils import checkpoint as ckpt_mod

from tests import oracle

jax.config.update("jax_platforms", "cpu")

STEPS = 12


def _worlds(shapes, seed=7, density=0.35):
    return [
        oracle.random_board(h, w, seed=seed + i, density=density)
        for i, (h, w) in enumerate(shapes)
    ]


def _refs(worlds, steps=STEPS):
    return [
        np.asarray(stencil.run(jnp.asarray(w.copy()), steps)) for w in worlds
    ]


# -- bucketing ---------------------------------------------------------------


def test_bucket_shape_rounds_up():
    assert bucket_shape(48, 64, 64) == (64, 64)
    assert bucket_shape(64, 64, 64) == (64, 64)
    assert bucket_shape(65, 1, 64) == (128, 64)
    with pytest.raises(ValueError):
        bucket_shape(8, 8, 0)


def test_bucketize_groups_and_masks():
    buckets = bucketize([(64, 64), (48, 32), (64, 64), (96, 96)], 64)
    assert [(b.shape, b.batch, b.masked) for b in buckets] == [
        ((64, 64), 3, True),  # two exact 64x64 + one padded 48x32
        ((128, 128), 1, True),
    ]
    # Exact-only bucket is unmasked.
    (b,) = bucketize([(64, 64), (64, 64)], 64)
    assert not b.masked and b.indices == (0, 1)


def test_resolve_bucket_engine():
    shapes = [(64, 64), (48, 32)]
    exact = Bucket(shape=(64, 64), indices=(0,), masked=False)
    masked = Bucket(shape=(64, 64), indices=(0, 1), masked=True)
    assert resolve_bucket_engine("auto", exact, shapes) == "bitpack"
    assert resolve_bucket_engine("dense", masked, shapes) == "dense"
    # The fused kernel has no masked form: documented bit-exact fallback.
    assert resolve_bucket_engine("pallas_bitpack", masked, shapes) == "bitpack"
    # Unpackable world width: auto degrades, explicit bitpack refuses.
    shapes_odd = [(64, 64), (48, 20)]
    masked_odd = Bucket(shape=(64, 64), indices=(0, 1), masked=True)
    assert resolve_bucket_engine("auto", masked_odd, shapes_odd) == "dense"
    with pytest.raises(ValueError, match="pack"):
        resolve_bucket_engine("bitpack", masked_odd, shapes_odd)


# -- bit-equality per tier ---------------------------------------------------


@pytest.mark.parametrize("engine", ["dense", "bitpack", "pallas_bitpack"])
def test_exact_batch_bit_equal_to_sequential(engine):
    worlds = _worlds([(32, 64)] * 3)
    refs = _refs(worlds)
    brt = GolBatchRuntime(
        worlds=[w.copy() for w in worlds], engine=engine, bucket_quantum=32
    )
    _, out = brt.run(STEPS)
    assert brt._engines == [engine]
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


@pytest.mark.parametrize("engine", ["dense", "bitpack", "auto"])
def test_masked_mixed_sizes_bit_equal(engine):
    # One bucket (quantum 64) holding 64x64 exact, 48x64 and 40x32 padded
    # — the masked program must reproduce each world's own torus.
    worlds = _worlds([(64, 64), (48, 64), (40, 32)])
    refs = _refs(worlds)
    brt = GolBatchRuntime(worlds=[w.copy() for w in worlds], engine=engine)
    assert len(brt.buckets) == 1 and brt.buckets[0].masked
    _, out = brt.run(STEPS)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


def test_masked_dense_handles_unpackable_widths():
    worlds = _worlds([(30, 50), (17, 23), (64, 64)])
    refs = _refs(worlds)
    brt = GolBatchRuntime(worlds=[w.copy() for w in worlds], engine="auto")
    assert "dense" in brt._engines
    _, out = brt.run(STEPS)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


def test_padding_stays_dead():
    # A full-live world in a padded bucket: no cell may leak outside.
    worlds = [np.ones((40, 40), np.uint8), np.zeros((64, 64), np.uint8)]
    brt = GolBatchRuntime(worlds=[w.copy() for w in worlds], engine="dense")
    fn, masked = brt._evolver(0, 3)
    assert masked
    stack, hs, ws = brt._stack(brt.buckets[0])
    out = np.asarray(fn(stack, hs, ws))
    pad = out[0].copy()
    pad[:40, :40] = 0
    assert not pad.any()


@pytest.mark.parametrize("engine", ["dense", "bitpack", "pallas_bitpack"])
def test_worlds_mesh_sharding_bit_equal(engine):
    # B=8 on the 8-device CPU mesh: every bucket actually shards.
    worlds = _worlds([(32, 64)] * 8)
    refs = _refs(worlds)
    mesh = make_batch_mesh()
    brt = GolBatchRuntime(
        worlds=[w.copy() for w in worlds],
        engine=engine,
        mesh=mesh,
        bucket_quantum=32,
    )
    assert brt._bucket_mesh(brt.buckets[0]) is mesh
    _, out = brt.run(STEPS)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


def test_worlds_mesh_indivisible_batch_falls_back_unsharded():
    worlds = _worlds([(32, 32)] * 3)  # 3 % 8 != 0
    brt = GolBatchRuntime(
        worlds=[w.copy() for w in worlds], engine="dense",
        mesh=make_batch_mesh(),
    )
    assert brt._bucket_mesh(brt.buckets[0]) is None
    _, out = brt.run(4)
    for i, ref in enumerate(_refs(worlds, 4)):
        np.testing.assert_array_equal(out[i], ref)


def test_masked_worlds_mesh_bit_equal():
    worlds = _worlds([(64, 64), (48, 32)] * 4)  # one masked bucket, B=8
    refs = _refs(worlds)
    brt = GolBatchRuntime(
        worlds=[w.copy() for w in worlds], engine="auto",
        mesh=make_batch_mesh(),
    )
    assert len(brt.buckets) == 1 and brt.buckets[0].masked
    assert brt._bucket_mesh(brt.buckets[0]) is not None
    _, out = brt.run(STEPS)
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(out[i], ref)


# -- retrace / program identity ----------------------------------------------


def test_builder_returns_cached_programs():
    a = compiled_batch_evolver("bitpack", 8, False, 512, None)
    b = compiled_batch_evolver("bitpack", 8, False, 512, None)
    assert a is b


def test_trace_identity_single_world_jaxprs_unchanged():
    """Building batched programs must leave every single-world engine's
    jaxpr byte-identical — the PR 2 trace-identity pin, extended."""
    from gol_tpu.analysis import walker

    spec = jax.ShapeDtypeStruct((64, 64), np.uint8)

    def single_world_jaxprs():
        out = {}
        for engine in ("dense", "bitpack"):
            rt = GolRuntime(
                geometry=Geometry(size=64, num_ranks=1), engine=engine
            )
            fn, dynamic, static = rt._evolve_fn(4)
            out[engine] = str(walker.trace_jaxpr(fn, spec, *dynamic, *static))
        return out

    before = single_world_jaxprs()
    # Build + run batched programs for the same tiers and geometry.
    worlds = _worlds([(64, 64), (48, 64)])
    for engine in ("dense", "bitpack"):
        GolBatchRuntime(
            worlds=[w.copy() for w in worlds], engine=engine
        ).run(4)
    after = single_world_jaxprs()
    assert before == after


# -- telemetry (schema v4) ---------------------------------------------------


def _read_events(path):
    return [json.loads(ln) for ln in open(path)]


def test_batch_telemetry_v4_events(tmp_path):
    from gol_tpu import telemetry

    # v4 introduced the batch fields; the current schema (v5 at this
    # round) keeps them additive-forever.
    assert telemetry.SCHEMA_VERSION >= 4
    worlds = _worlds([(64, 64), (48, 32), (64, 64)])
    brt = GolBatchRuntime(
        worlds=[w.copy() for w in worlds],
        engine="auto",
        checkpoint_every=4,
        checkpoint_dir=str(tmp_path / "ck"),
        telemetry_dir=str(tmp_path / "tl"),
        run_id="b4",
    )
    report, _ = brt.run(8)
    recs = _read_events(tmp_path / "tl" / "b4.rank0.jsonl")
    head = recs[0]
    assert head["schema"] == telemetry.SCHEMA_VERSION
    assert head["config"]["driver"] == "batch"
    assert head["config"]["buckets"][0]["B"] == 3
    compiles = [r for r in recs if r["event"] == "compile"]
    assert all("batch" in c for c in compiles)
    chunks = [r for r in recs if r["event"] == "chunk"]
    assert len(chunks) == 2  # one bucket x two 4-gen chunks
    for c in chunks:
        b = c["batch"]
        assert b["bucket"] == [64, 64] and b["B"] == 3 and b["masked"]
        assert b["per_world_updates_per_sec"] > 0
    assert [r["event"] for r in recs].count("checkpoint") == 2
    assert recs[-1]["event"] == "summary"


def test_batch_summarize_renders_and_exits_zero(tmp_path, capsys):
    import io

    from gol_tpu.telemetry import summarize as summ_mod

    worlds = _worlds([(64, 64)] * 2)
    GolBatchRuntime(
        worlds=worlds, engine="bitpack",
        telemetry_dir=str(tmp_path / "tl"), run_id="bs",
    ).run(6)
    out = io.StringIO()
    assert summ_mod.summarize(str(tmp_path / "tl"), out) == 0
    text = out.getvalue()
    assert "driver=batch" in text
    assert "B=2" in text and "/world" in text


# -- checkpoints on the PR 4 resilience path ---------------------------------


def test_batch_snapshot_roundtrip_and_corruption(tmp_path):
    worlds = _worlds([(16, 16), (24, 32)])
    path = ckpt_mod.batch_checkpoint_path(str(tmp_path), 5)
    ckpt_mod.save_batch(path, worlds, 5)
    snap = ckpt_mod.load_batch(path)
    assert snap.generation == 5
    for got, want in zip(snap.boards, worlds):
        np.testing.assert_array_equal(got, want)
    assert ckpt_mod.verify_snapshot(path) == 5
    with open(path, "r+b") as f:
        f.seek(40)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(ckpt_mod.CorruptSnapshotError):
        ckpt_mod.load_batch(path)


def test_batch_resume_completes_bit_identically(tmp_path):
    worlds = _worlds([(64, 64), (48, 32), (96, 96)])
    full_rt = GolBatchRuntime(worlds=[w.copy() for w in worlds])
    _, full = full_rt.run(8)

    ck = str(tmp_path / "ck")
    GolBatchRuntime(
        worlds=[w.copy() for w in worlds], checkpoint_every=2,
        checkpoint_dir=ck,
    ).run(4)
    resume, info = resilience.resolve_auto_resume(ck, kind="batch")
    assert info["generation"] == 4 and not info["fallback"]
    rt2 = GolBatchRuntime(
        worlds=[w.copy() for w in worlds], checkpoint_every=2,
        checkpoint_dir=ck,
    )
    _, done = rt2.run(4, resume=resume)
    assert rt2.generation == 8
    for i, ref in enumerate(full):
        np.testing.assert_array_equal(done[i], ref)


def test_batch_auto_resume_falls_back_past_corruption(tmp_path):
    worlds = _worlds([(32, 32), (24, 16)])
    ck = str(tmp_path / "ck")
    GolBatchRuntime(
        worlds=[w.copy() for w in worlds], checkpoint_every=2,
        checkpoint_dir=ck,
    ).run(6)
    snaps = ckpt_mod.list_snapshots(ck, kind="batch")
    assert len(snaps) == 3
    with open(snaps[-1], "r+b") as f:
        f.seek(33)
        f.write(b"\xff\xff\xff\xff")
    resume, info = resilience.resolve_auto_resume(ck, kind="batch")
    assert info["generation"] == 4 and info["fallback"]
    import os as _os

    assert info["skipped"] == [_os.path.basename(snaps[-1])]
    # The fallback resume still lands bit-identically on the full run.
    _, full = GolBatchRuntime(worlds=[w.copy() for w in worlds]).run(8)
    rt2 = GolBatchRuntime(
        worlds=[w.copy() for w in worlds], checkpoint_dir=ck,
    )
    _, done = rt2.run(4, resume=resume)
    for i, ref in enumerate(full):
        np.testing.assert_array_equal(done[i], ref)


def test_batch_retention_gc(tmp_path):
    worlds = _worlds([(16, 16)])
    ck = str(tmp_path / "ck")
    GolBatchRuntime(
        worlds=worlds, checkpoint_every=1, checkpoint_dir=ck,
        keep_snapshots=2,
    ).run(6)
    snaps = ckpt_mod.list_snapshots(ck, kind="batch")
    assert len(snaps) == 2
    assert [ckpt_mod.snapshot_generation(p) for p in snaps] == [5, 6]


def test_batch_preemption_checkpoints_and_resumes(tmp_path):
    worlds = _worlds([(48, 64), (64, 64)])
    _, full = GolBatchRuntime(worlds=[w.copy() for w in worlds]).run(9)

    ck = str(tmp_path / "ck")
    tl = str(tmp_path / "tl")
    rt = GolBatchRuntime(
        worlds=[w.copy() for w in worlds], checkpoint_every=3,
        checkpoint_dir=ck, telemetry_dir=tl, run_id="pre",
    )
    resilience.request_preemption()
    try:
        with pytest.raises(resilience.Preempted) as exc:
            rt.run(9)
    finally:
        resilience.clear_preemption()
    assert exc.value.generation == 3
    recs = _read_events(tmp_path / "tl" / "pre.rank0.jsonl")
    pre = [r for r in recs if r["event"] == "preempt"]
    assert pre and pre[0]["checkpointed"] and pre[0]["generation"] == 3
    # Relaunch with the remaining work: bit-identical to uninterrupted.
    resume, info = resilience.resolve_auto_resume(ck, kind="batch")
    assert info["generation"] == 3
    rt2 = GolBatchRuntime(
        worlds=[w.copy() for w in worlds], checkpoint_every=3,
        checkpoint_dir=ck,
    )
    _, done = rt2.run(6, resume=resume)
    for i, ref in enumerate(full):
        np.testing.assert_array_equal(done[i], ref)


def test_batch_resume_shape_mismatch_rejected(tmp_path):
    path = ckpt_mod.batch_checkpoint_path(str(tmp_path), 2)
    ckpt_mod.save_batch(path, _worlds([(16, 16)]), 2)
    rt = GolBatchRuntime(worlds=_worlds([(32, 32)]))
    with pytest.raises(ValueError, match="configured"):
        rt.run(2, resume=path)
    rt2 = GolBatchRuntime(worlds=_worlds([(16, 16), (16, 16)]))
    with pytest.raises(ValueError, match="worlds"):
        rt2.run(2, resume=path)


# -- compile cache -----------------------------------------------------------


def test_compile_cache_populates(tmp_path):
    cc = str(tmp_path / "cc")
    worlds = _worlds([(32, 32)])
    brt = GolBatchRuntime(worlds=worlds, engine="dense", compile_cache=cc)
    brt.run(3)
    assert cache_entries(cc)
    # (Cross-process hit behavior is asserted by scripts/batch_smoke.py —
    # in-process a second run is served by the jit cache before XLA.)


# -- CLI ---------------------------------------------------------------------


def test_cli_batch_smoke(tmp_path, capsys):
    from gol_tpu import cli

    rc = cli.main([
        "6", "64", "8", "512", "1",
        "--batch", "4", "--batch-sizes", "64,96",
        "--outdir", str(tmp_path),
        "--telemetry", str(tmp_path / "tl"), "--run-id", "c",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "TOTAL DURATION" in out and "BATCH" in out
    for i in range(4):
        assert (tmp_path / f"world_{i:04d}" / "Rank_0_of_1.txt").exists()
    # World 0 (size 64) dump equals the sequential single-world CLI dump.
    seq = tmp_path / "seq"
    assert cli.main(["6", "64", "8", "512", "1", "--outdir", str(seq)]) == 0
    capsys.readouterr()
    a = (tmp_path / "world_0000" / "Rank_0_of_1.txt").read_bytes()
    b = (seq / "Rank_0_of_1.txt").read_bytes()
    assert a == b


def test_cli_batch_rejections(tmp_path, capsys):
    from gol_tpu import cli

    base = ["6", "64", "4", "512", "0", "--outdir", str(tmp_path)]
    for extra, msg in [
        (["--batch", "-1"], "--batch must be"),
        (["--batch-sizes", "64"], "--batch-sizes applies"),
        (["--batch", "2", "--halo", "stale_t0"], "fresh halos"),
        (["--batch", "2", "--rule", "B36/S23"], "B3/S23"),
        (["--batch", "2", "--stats", "--telemetry", str(tmp_path)],
         "single-world"),
        # (--batch + --guard-every is now a supported combination —
        # PR 10's batched guard; see tests/test_guard_tiers.py.)
        (["--batch", "2", "--mesh", "2d"], "1-D"),
        (["--batch", "2", "--engine", "pallas"], "no batched tier"),
        (["--batch", "2", "--batch-sizes", "xyz"], "no sizes"),
    ]:
        rc = cli.main(base + extra)
        out = capsys.readouterr().out
        assert rc == 255, extra
        assert msg in out, (extra, out)


def test_cli_batch_auto_resume_total_target(tmp_path, capsys):
    from gol_tpu import cli

    args = [
        "6", "64", "8", "512", "0", "--batch", "2",
        "--checkpoint-every", "4",
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--auto-resume", "--outdir", str(tmp_path),
    ]
    assert cli.main(args) == 0
    capsys.readouterr()
    # Identical argv relaunch: already at the total target -> 0 more gens.
    assert cli.main(args) == 0
    out = capsys.readouterr().out
    assert "auto-resume: generation 8" in out


# -- batchbench --------------------------------------------------------------


def test_batchbench_writes_artifact(tmp_path):
    from benchmarks import batchbench

    out = tmp_path / "BATCH_test.json"
    rc = batchbench.main([
        "--size", "32", "--iters", "8", "--bs", "1,2",
        "--engine", "bitpack", "--repeats", "1", "--out", str(out),
    ])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["backend"] == "cpu"
    assert [r["B"] for r in data["rows"]] == [1, 2]
    for row in data["rows"]:
        assert row["per_world_speedup_vs_sequential"] > 0
        assert "device_fit" in row


def test_committed_batch_artifact_is_valid():
    import pathlib

    path = pathlib.Path(__file__).parent.parent / "BATCH_r06.json"
    data = json.loads(path.read_text())
    assert data["rows"] and "command" in data
    assert all("per_world_speedup_vs_sequential" in r for r in data["rows"])


# -- verifier ----------------------------------------------------------------


def test_batchcheck_matrix_passes():
    from gol_tpu.analysis import batchcheck
    from gol_tpu.analysis.report import FAIL

    reports = batchcheck.run_batch_checks()
    assert len(reports) == 7
    for rep in reports:
        assert all(c.status != FAIL for c in rep.checks), rep.config_name


def test_batchcheck_catches_coupled_worlds():
    """A program that mixes worlds must fail batch-invariance."""
    from gol_tpu.analysis import batchcheck

    cfg = batchcheck.BatchConfig(
        "broken", "dense", False, False, batch=3, shape=(16, 32)
    )

    def broken(stack):
        rolled = jnp.roll(stack, 1, axis=0)  # world i reads world i-1
        return jax.vmap(stencil.step)(rolled)

    res = batchcheck.check_batch_invariance(cfg, jax.jit(broken), None)
    assert res.status == "FAIL"
