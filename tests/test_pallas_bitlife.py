"""Fused bit-packed Pallas kernel vs. the oracle (interpreter mode on CPU).

The top perf tier: the carry-save adder tree of bitlife runs fused over
VMEM tiles of the packed board.  Interpreter mode executes the same kernel
logic on CPU, covering the DMA halo indexing (mod-H row wrap), the lane-
roll word ring, and the logical-shift emulation on int32.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.ops import pallas_bitlife

from tests import oracle


@pytest.mark.parametrize("shape", [(32, 64), (64, 128), (8, 32), (16, 256)])
@pytest.mark.parametrize("steps", [1, 3])
def test_matches_oracle(shape, steps):
    h, w = shape
    board = oracle.random_board(h, w, seed=h + w + steps)
    got = np.asarray(pallas_bitlife.evolve(jnp.asarray(board), steps, 512))
    np.testing.assert_array_equal(got, oracle.run_torus(board, steps))


def test_blinker_wrap():
    from gol_tpu.models import patterns

    board = patterns.init_global(4, 64, num_ranks=1)
    got = np.asarray(pallas_bitlife.evolve(jnp.asarray(board), 2, 512))
    np.testing.assert_array_equal(got, board)  # period 2 across the x-wrap


def test_tile_smaller_than_board():
    """Multi-tile grid: the row-wrap halo DMAs cross tile boundaries."""
    board = oracle.random_board(64, 64, seed=9)
    got = np.asarray(pallas_bitlife.evolve(jnp.asarray(board), 4, 16))
    np.testing.assert_array_equal(got, oracle.run_torus(board, 4))


@pytest.mark.parametrize("k", [2, 5, 8, 16])
@pytest.mark.slow  # minutes-scale interpret-mode sweep; run with -m slow
def test_multi_step_matches_sequential(k):
    """Temporal blocking: k fused generations == k single-step launches."""
    from jax import lax

    from gol_tpu.ops import bitlife

    board = oracle.random_board(64, 64, seed=20 + k)
    packed = lax.bitcast_convert_type(
        bitlife.pack(jnp.asarray(board)), jnp.int32
    )
    ref = packed
    for _ in range(k):
        ref = pallas_bitlife.step_pallas_packed(ref, 16)
    got = pallas_bitlife.multi_step_pallas_packed(packed, 16, k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_multi_step_remainder_path():
    """steps not divisible by the block: full blocks + one remainder launch."""
    board = oracle.random_board(32, 64, seed=31)
    got = np.asarray(pallas_bitlife.evolve(jnp.asarray(board), 21, 512))
    np.testing.assert_array_equal(got, oracle.run_torus(board, 21))


def test_multi_step_depth_validation():
    packed = jnp.zeros((64, 2), jnp.int32)
    with pytest.raises(ValueError, match="pad"):
        pallas_bitlife.multi_step_pallas_packed(packed, 8, 16)
    with pytest.raises(ValueError, match=">= 1"):
        pallas_bitlife.multi_step_pallas_packed(packed, 8, 0)


def test_pick_block_respects_geometry():
    # Default block depth re-tuned to 8 in round 3 (RPC-amortized
    # x10240 sweep: k=8 beats k=16 by its recompute-factor gap).
    assert pallas_bitlife._pick_block(1000, 256) == pallas_bitlife._BLOCK
    assert pallas_bitlife._pick_block(5, 256) == 5
    assert pallas_bitlife._pick_block(1000, 8) == 8
    assert pallas_bitlife._pick_block(1000, 256, block=16) == 16


def test_pick_tile():
    assert pallas_bitlife.pick_tile(64, 2, 512) == 64
    assert pallas_bitlife.pick_tile(64, 2, 16) == 16
    with pytest.raises(ValueError, match="divisible"):
        pallas_bitlife.pick_tile(12, 2, 512)


def test_width_must_pack():
    board = jnp.zeros((32, 48), jnp.uint8)  # 48 % 32 != 0
    with pytest.raises(ValueError, match="divisible"):
        pallas_bitlife.evolve(board, 1, 512)
