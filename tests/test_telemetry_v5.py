"""Schema v5 (activity-gated tier fields) + v1–v4 back-compat.

Companion to tests/test_telemetry.py (v1), test_telemetry_v2.py,
test_telemetry_v3.py and test_telemetry_v4.py.  Here:

- the v5 additions round-trip: the ``activity`` block on ``chunk``
  events (tile geometry, active/computed/skipped tile-generations,
  fallback count, active fraction — docs/SPARSE.md);
- **back-compat**: ALL FOUR committed fixtures — PR 2 (v1), PR 3 (v2),
  PR 5 (v3) and PR 6 (v4) — still load, and a directory holding
  v1 + v2 + v3 + v4 + a freshly-written v5 stream merges and renders in
  one ``summarize`` pass (exit 0), while a bogus schema still exits 2;
- the activity fallback-storm anomaly flags a run whose every
  generation overflowed the worklist, and stays quiet otherwise.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
}

ACTIVITY_BLOCK = {
    "tile": 64,
    "tiles": 256,
    "tile_gens": 2048,
    "active_tile_gens": 180,
    "computed_tile_gens": 180,
    "skipped_tile_gens": 1868,
    "fallback_gens": 0,
    "active_fraction": 180 / 2048,
}


def _v5_stream(directory, run_id="v5", fallback_storm=False):
    block = dict(ACTIVITY_BLOCK)
    if fallback_storm:
        block.update(
            fallback_gens=8,
            computed_tile_gens=2048,
            skipped_tile_gens=0,
        )
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "2d", "engine": "activity", "resolved_engine":
             "activity", "height": 1024, "width": 1024}
        )
        ev.compile_event(8, 0.01, 0.11)
        ev.chunk_event(0, 8, 8, 0.002, 8388608, None, activity=dict(block))
        return ev.path


def test_v5_activity_fields_roundtrip(tmp_path):
    path = _v5_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 5
    assert set(telemetry.SUPPORTED_SCHEMAS) >= {1, 2, 3, 4, 5}
    chunk = recs[2]
    assert chunk["activity"]["tile"] == 64
    assert chunk["activity"]["skipped_tile_gens"] == 1868
    assert (
        chunk["activity"]["tile_gens"]
        == chunk["activity"]["computed_tile_gens"]
        + chunk["activity"]["skipped_tile_gens"]
    )


def test_committed_fixture_schemas_are_v1_to_v4():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v1_to_v5_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v5_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    # One run section per fixture + the fresh v5 stream.
    for run_id in ("pr2run", "pr3run", "pr5run", "pr6run", "v5"):
        assert run_id in out
    # The v5 stream is newest, so its chunk table (with the activity
    # column) is the one rendered in detail.
    assert "act 8.8% skip 1868/2048" in out


def test_bogus_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": 99, "run_id": "bad",
             "process_index": 0, "process_count": 1, "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2


def test_fallback_storm_anomaly(tmp_path, capsys):
    _v5_stream(tmp_path, run_id="storm", fallback_storm=True)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "activity fallback storm" in out


def test_quiet_run_has_no_fallback_storm_flag(tmp_path, capsys):
    _v5_stream(tmp_path, run_id="quiet")
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    assert "fallback storm" not in capsys.readouterr().out
