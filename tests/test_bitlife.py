"""Bit-packed engine vs. the dense engine and the NumPy oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from gol_tpu.ops import bitlife, stencil

from tests import oracle


random_board = oracle.random_board


@pytest.mark.parametrize("shape", [(8, 32), (16, 64), (7, 96), (1, 32), (40, 128)])
@pytest.mark.parametrize("seed", [0, 1])
def test_pack_unpack_roundtrip(shape, seed):
    board = random_board(*shape, seed)
    packed = bitlife.pack(jnp.asarray(board))
    assert packed.dtype == jnp.uint32
    assert packed.shape == (shape[0], shape[1] // 32)
    np.testing.assert_array_equal(np.asarray(bitlife.unpack(packed)), board)


def test_pack_rejects_unaligned_width():
    with pytest.raises(ValueError, match="divisible"):
        bitlife.pack(jnp.zeros((8, 33), jnp.uint8))


@pytest.mark.parametrize("shape", [(8, 32), (16, 64), (9, 96), (2, 32), (64, 128)])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_step_packed_matches_oracle(shape, seed):
    board = random_board(*shape, seed)
    packed = bitlife.pack(jnp.asarray(board))
    got = np.asarray(bitlife.unpack(bitlife.step_packed(packed)))
    np.testing.assert_array_equal(got, oracle.step_torus(board))


def test_word_boundary_and_wrap_columns():
    """Structures straddling a 32-bit word boundary and the x-wrap evolve
    correctly — the carry-bit path of the west/east lane shifts."""
    board = np.zeros((8, 64), np.uint8)
    board[3, 31] = board[3, 32] = board[3, 33] = 1  # blinker across words
    board[6, 63] = board[6, 0] = board[6, 1] = 1  # blinker across the wrap
    packed = bitlife.pack(jnp.asarray(board))
    one = np.asarray(bitlife.unpack(bitlife.step_packed(packed)))
    np.testing.assert_array_equal(one, oracle.step_torus(board))
    two = np.asarray(
        bitlife.unpack(bitlife.step_packed(bitlife.pack(jnp.asarray(one))))
    )
    np.testing.assert_array_equal(two, board)  # period 2


@pytest.mark.parametrize("steps", [0, 1, 7, 16])
def test_evolve_dense_io_matches_dense_engine(steps):
    board = random_board(24, 96, 5)
    got = np.asarray(bitlife.evolve_dense_io(jnp.asarray(board), steps))
    want = np.asarray(stencil.run(jnp.asarray(board), steps))
    np.testing.assert_array_equal(got, want)


def test_run_packed_long_evolution_matches_oracle():
    board = random_board(32, 32, 9)
    packed = bitlife.pack(jnp.asarray(board))
    got = np.asarray(bitlife.unpack(bitlife.run_packed(packed, 20)))
    np.testing.assert_array_equal(got, oracle.run_torus(board, 20))


def test_step_packed_rows_with_explicit_halos():
    """Row-sharded form: packed ghost rows reproduce the torus step."""
    board = random_board(12, 64, 13)
    packed = np.asarray(bitlife.pack(jnp.asarray(board)))
    got = np.asarray(
        bitlife.step_packed_rows(
            jnp.asarray(packed),
            jnp.asarray(np.roll(packed, 1, axis=0)),
            jnp.asarray(np.roll(packed, -1, axis=0)),
        )
    )
    np.testing.assert_array_equal(
        np.asarray(bitlife.unpack(got)), oracle.step_torus(board)
    )
