"""Schema v9 (fault-plane events) + v1–v8 back-compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..8}.py.
Here:

- the v9 additions round-trip: ``fault`` records one fired injection of
  the declarative fault plan, ``degraded`` one containment decision
  (docs/RESILIENCE.md);
- a REAL faulted guarded run emits ``fault`` records alongside the
  failing ``guard_audit`` it caused, through the run loops' drain;
- **back-compat**: ALL EIGHT committed fixtures — PR 2 (v1) through
  PR 10 (v8, a real pipelined run with halo blocks) — still load, and a
  directory holding v1–v8 + a fresh v9 stream merges and renders in one
  ``summarize`` pass (exit 0) with the fault line and the degraded
  anomaly, while a bogus schema still exits 2.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
    6: DATA / "telemetry_v6" / "pr8run.rank0.jsonl",
    7: DATA / "telemetry_v7" / "pr9run.rank0.jsonl",
    8: DATA / "telemetry_v8" / "pr10run.rank0.jsonl",
}


def _v9_stream(directory, run_id="v9"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "2d", "engine": "bitpack",
             "resolved_engine": "bitpack", "height": 64, "width": 64}
        )
        ev.compile_event(4, 0.01, 0.05)
        ev.chunk_event(0, 4, 4, 0.002, 16384, None)
        ev.fault_event(
            "board.bitflip", 4, row=5, col=7, value=-1
        )
        ev.degraded_event(
            "checkpoint", "retried", generation=4, attempt=1,
            detail="injected transient checkpoint IO error",
        )
        return ev.path


def test_v9_fault_degraded_roundtrip(tmp_path):
    path = _v9_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 9
    assert set(telemetry.SUPPORTED_SCHEMAS) >= set(range(1, 10))
    fault = next(r for r in recs if r["event"] == "fault")
    assert fault["site"] == "board.bitflip" and fault["generation"] == 4
    deg = next(r for r in recs if r["event"] == "degraded")
    assert deg["resource"] == "checkpoint" and deg["action"] == "retried"


def test_real_faulted_guarded_run_stamps_v9_records(tmp_path):
    """End to end: a guarded run with an armed fault plan drains the
    fired injection into a ``fault`` record, next to the failing audit."""
    from gol_tpu.models.state import Geometry
    from gol_tpu.resilience import faults
    from gol_tpu.runtime import GolRuntime
    from gol_tpu.utils import guard as guard_mod

    faults.install(
        faults.FaultPlan.from_obj(
            [{"site": "board.bitflip", "at": 6, "row": 5, "col": 7,
              "value": 165}]
        )
    )
    try:
        rt = GolRuntime(
            geometry=Geometry(size=64, num_ranks=1),
            engine="bitpack",
            telemetry_dir=str(tmp_path),
            run_id="faulted",
        )
        _, _, report = guard_mod.run_guarded(
            rt, pattern=4, iterations=6,
            config=guard_mod.GuardConfig(check_every=2),
        )
    finally:
        faults.clear()
    assert report.failures >= 1
    recs = [
        json.loads(ln) for ln in open(tmp_path / "faulted.rank0.jsonl")
    ]
    fault = [r for r in recs if r["event"] == "fault"]
    assert fault and fault[0]["site"] == "board.bitflip"
    assert any(
        r["event"] == "guard_audit" and not r["ok"] for r in recs
    )


def test_committed_fixture_schemas_are_v1_to_v8():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v8_fixture_is_a_real_pipelined_run():
    recs = [json.loads(ln) for ln in FIXTURES[8].open()]
    head = recs[0]
    assert head["config"]["shard_mode"] == "pipeline"
    assert head["config"]["halo_depth"] == 4
    chunks = [r for r in recs if r["event"] == "chunk"]
    assert chunks
    for c in chunks:
        assert c["halo"]["mode"] == "pipeline"
        assert c["halo"]["depth"] == 4


def test_v1_to_v9_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v9_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for run_id in (
        "pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "pr8run",
        "pr9run", "pr10run", "v9",
    ):
        assert run_id in out
    assert "faults: 1 injection(s) fired" in out
    assert "degraded: checkpoint retried" in out


def test_bogus_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": 99, "run_id": "bad",
             "process_index": 0, "process_count": 1, "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2
