"""Concurrency stress for the serving tier (docs/ANALYSIS.md).

N client threads hammer /simulate + /result + /metrics over real HTTP
while the main thread drives the batch loop and deadline requests
expire mid-flight.  The assertions are the serving tier's concurrency
contract: every admitted id reaches exactly one terminal journal state
(no duplicate completes, no resurrection), double-submissions admit
once, and a terminal HTTP answer always carries its payload.

Runs with the lockwatch recorder on (GOL_LOCKWATCH=1): afterwards the
dynamically observed lock-acquisition edges must be acyclic AND a
subset of the static lock-order graph lockcheck proved — the runtime
witness that the AST model covers what the threads actually did.
"""

from __future__ import annotations

import collections
import json
import pathlib
import threading
import time
import urllib.request

import jax

from gol_tpu.analysis import hostwalk, lockcheck, lockwatch
from gol_tpu.serve import journal as journal_mod
from gol_tpu.serve.client import Backpressure, SimClient
from gol_tpu.serve.scheduler import ServeScheduler
from gol_tpu.serve.server import ServeServer
from gol_tpu.telemetry.metrics import MetricsRegistry

jax.config.update("jax_platforms", "cpu")

N_CLIENTS = 5
REQS_PER_CLIENT = 4  # odd ordinals carry an already-lapsed deadline


def _client_ids(i: int):
    return [f"c{i}-r{j}" for j in range(REQS_PER_CLIENT)]


def _hammer(base_url: str, i: int, out: dict, errors: list) -> None:
    c = SimClient(base_url, timeout=30.0)
    try:
        for j, rid in enumerate(_client_ids(i)):
            req = {
                "id": rid, "pattern": 4, "size": 32, "generations": 6,
            }
            if j % 2 == 1:
                req["deadline_s"] = 0.0
                req["generations"] = 500
            for attempt in range(50):
                try:
                    c.submit(req)
                    break
                except Backpressure:
                    time.sleep(0.05)
            else:
                raise RuntimeError(f"{rid}: backpressure never cleared")
            # double-submit the same id: admission must stay
            # exactly-once even while other threads race the queue
            c.submit(req)
            with urllib.request.urlopen(
                base_url + "/metrics", timeout=30.0
            ) as r:
                assert r.status == 200 and b"gol_serve" in r.read()
        for rid in _client_ids(i):
            out[rid] = c.wait_for(rid, timeout_s=120.0, poll_s=0.01)
    except BaseException as e:  # surfaced by the main thread
        errors.append((i, repr(e)))


def test_stress_exactly_once_terminal_and_lock_witness(
    tmp_path, monkeypatch
):
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    lockwatch.reset()

    state_dir = tmp_path / "state"
    registry = MetricsRegistry()
    sched = ServeScheduler(
        str(state_dir), quantum=32, slots=4, chunk=2, queue_depth=64,
        telemetry_dir=str(tmp_path / "tm"), run_id="stress",
        registry=registry,
    )
    srv = ServeServer(sched, 0, registry=registry)
    base = f"http://127.0.0.1:{srv.port}"

    results: dict = {}
    errors: list = []
    clients = [
        threading.Thread(target=_hammer, args=(base, i, results, errors))
        for i in range(N_CLIENTS)
    ]
    stop = threading.Event()

    def drive():
        while not stop.is_set():
            if not sched.run_once():
                time.sleep(0.002)

    driver = threading.Thread(target=drive)
    try:
        driver.start()
        for t in clients:
            t.start()
        for t in clients:
            t.join(timeout=180.0)
            assert not t.is_alive(), "client thread hung"
        stop.set()
        driver.join(timeout=60.0)
        assert not driver.is_alive()
        sched.drain()
        sched.run_until_drained()
    finally:
        stop.set()
        srv.close()
        sched.close()

    assert errors == []

    # every request reached a terminal payload, deadlines really fired
    all_ids = [rid for i in range(N_CLIENTS) for rid in _client_ids(i)]
    assert sorted(results) == sorted(all_ids)
    for rid, payload in results.items():
        assert payload["status"] in ("done", "expired"), (rid, payload)
        assert payload["id"] == rid
    expired = [r for r in results.values() if r["status"] == "expired"]
    done = [r for r in results.values() if r["status"] == "done"]
    assert len(expired) == N_CLIENTS * (REQS_PER_CLIENT // 2)
    assert len(done) == N_CLIENTS * (REQS_PER_CLIENT - REQS_PER_CLIENT // 2)

    # journal: exactly one admit and exactly one terminal per id
    entries, torn = journal_mod.replay(str(state_dir / "journal.jsonl"))
    assert torn == 0
    assert sorted(entries) == sorted(all_ids)
    for rid, entry in entries.items():
        assert entry["status"] in ("completed", "cancelled"), (rid, entry)
    counts: dict = collections.defaultdict(collections.Counter)
    for seg in sorted(pathlib.Path(state_dir).glob("journal*.jsonl")):
        for ln in open(seg):
            rec = json.loads(ln)
            counts[rec["id"]][rec["rec"]] += 1
    for rid in all_ids:
        assert counts[rid]["admit"] == 1, (rid, counts[rid])
        terminal = counts[rid]["complete"] + counts[rid]["cancel"]
        assert terminal == 1, (rid, counts[rid])

    # the registry saw the run and still renders
    text = registry.render()
    assert "gol_serve" in text

    # lockwatch witness: the dynamic acquisition graph is acyclic and
    # inside the static lock-order graph lockcheck proved
    assert lockwatch.acquire_counts().get("ServeScheduler._lock", 0) > 0
    assert lockwatch.find_cycle() is None
    serve_cell = next(
        c for c in lockcheck.default_lock_matrix()
        if c.name == "lock/serve"
    )
    prog = hostwalk.Program.load(serve_cell.modules)
    walker = lockcheck._CellWalker(prog, serve_cell)
    walker.run()
    unexpected = lockwatch.check(set(walker.edges))
    assert unexpected == set(), unexpected
