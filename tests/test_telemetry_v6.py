"""Schema v6 (span attribution) + v1–v5 back-compat.

Companion to tests/test_telemetry.py (v1) and test_telemetry_v{2..5}.py.
Here:

- the v6 additions round-trip: the ``spans`` block on ``chunk`` events
  (per-phase host seconds between force_ready fences —
  docs/OBSERVABILITY.md);
- **back-compat**: ALL FIVE committed fixtures — PR 2 (v1), PR 3 (v2),
  PR 5 (v3), PR 6 (v4) and PR 7 (v5) — still load, and a directory
  holding v1–v5 + a freshly-written v6 stream merges and renders in one
  ``summarize`` pass (exit 0), while a bogus schema still exits 2;
- real runs emit spans on every chunk whose dispatch+ready seconds
  are ≤ and within tolerance of the chunk's fenced wall, across the
  2-D runtime, the guarded loop, and the batch runtime;
- ``summarize`` renders the span phase-breakdown table and ``watch``
  the per-phase share line.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax
import numpy as np

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
FIXTURES = {
    1: DATA / "telemetry_v1" / "pr2run.rank0.jsonl",
    2: DATA / "telemetry_v2" / "pr3run.rank0.jsonl",
    3: DATA / "telemetry_v3" / "pr5run.rank0.jsonl",
    4: DATA / "telemetry_v4" / "pr6run.rank0.jsonl",
    5: DATA / "telemetry_v5" / "pr7run.rank0.jsonl",
}

SPANS_BLOCK = {
    "dispatch": 0.0004,
    "ready": 0.0016,
    "checkpoint": 0.0002,
    "telemetry": 0.0001,
    "preempt_poll": 0.00001,
}


def _v6_stream(directory, run_id="v6"):
    with telemetry.EventLog(
        str(directory), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "2d", "engine": "auto", "resolved_engine": "bitpack",
             "height": 256, "width": 256}
        )
        ev.compile_event(8, 0.01, 0.11)
        ev.chunk_event(
            0, 8, 8, 0.002, 524288, None, spans=dict(SPANS_BLOCK)
        )
        ev.chunk_event(
            1, 8, 16, 0.002, 524288, None, spans=dict(SPANS_BLOCK)
        )
        return ev.path


def test_v6_spans_roundtrip(tmp_path):
    path = _v6_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION >= 6
    assert set(telemetry.SUPPORTED_SCHEMAS) >= {1, 2, 3, 4, 5, 6}
    chunk = recs[2]
    assert chunk["spans"]["dispatch"] == 0.0004
    assert chunk["spans"]["preempt_poll"] == 0.00001


def test_committed_fixture_schemas_are_v1_to_v5():
    for want, fixture in FIXTURES.items():
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v1_to_v6_merge_renders(tmp_path, capsys):
    for fixture in FIXTURES.values():
        shutil.copy(fixture, tmp_path / fixture.name)
    _v6_stream(tmp_path)
    assert summ_mod.main(["summarize", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    # One run section per fixture + the fresh v6 stream.
    for run_id in ("pr2run", "pr3run", "pr5run", "pr6run", "pr7run", "v6"):
        assert run_id in out
    # The v6 stream is newest, so its span table renders in detail.
    assert "spans: phase" in out
    assert "dispatch" in out


def test_bogus_schema_still_exits_2(tmp_path):
    (tmp_path / "bad.rank0.jsonl").write_text(
        json.dumps(
            {"event": "run_header", "t": 0.0, "schema": 99, "run_id": "bad",
             "process_index": 0, "process_count": 1, "config": {}}
        )
        + "\n"
    )
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2


def test_watch_renders_span_shares(tmp_path, capsys):
    _v6_stream(tmp_path)
    assert summ_mod.main(["watch", str(tmp_path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "spans: " in out
    assert "ready" in out


# -- real-run span emission ---------------------------------------------------


def _chunks(directory, run_id):
    recs = [
        json.loads(ln)
        for ln in open(pathlib.Path(directory) / f"{run_id}.rank0.jsonl")
    ]
    return [r for r in recs if r["event"] == "chunk"]


def _assert_span_invariants(chunks, guard=False):
    assert chunks, "run emitted no chunk events"
    for c in chunks:
        spans = c.get("spans")
        assert spans, f"chunk {c['index']} has no spans block"
        assert all(v >= 0.0 for v in spans.values()), spans
        # dispatch+ready partition the fenced wall: never (meaningfully)
        # more, and most of it — the split is measured inside the same
        # t0..dt window wall_s comes from.
        inner = spans["dispatch"] + spans["ready"]
        assert inner <= c["wall_s"] * 1.05 + 1e-4, (inner, c["wall_s"])
        assert inner >= c["wall_s"] * 0.5, (inner, c["wall_s"])
    if guard:
        assert any("audit" in c["spans"] for c in chunks)


def test_runtime_spans_cover_chunk_walls(tmp_path):
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="bitpack",
        checkpoint_every=8,
        checkpoint_dir=str(tmp_path / "ck"),
        telemetry_dir=str(tmp_path / "t"),
        run_id="spanrun",
    )
    rt.run(pattern=6, iterations=32)
    chunks = _chunks(tmp_path / "t", "spanrun")
    assert len(chunks) == 4
    _assert_span_invariants(chunks)
    # Boundary phases land on the FOLLOWING chunk's block (chunk 0 has
    # none to inherit yet).
    assert "checkpoint" not in chunks[0]["spans"]
    assert all("checkpoint" in c["spans"] for c in chunks[1:])
    assert all("telemetry" in c["spans"] for c in chunks[1:])
    assert all("preempt_poll" in c["spans"] for c in chunks[1:])


def test_guarded_spans_carry_guard_phases(tmp_path):
    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime
    from gol_tpu.utils import guard as guard_mod

    rt = GolRuntime(
        geometry=Geometry(size=64, num_ranks=1),
        engine="dense",
        telemetry_dir=str(tmp_path / "t"),
        run_id="guardspan",
    )
    guard_mod.run_guarded(
        rt,
        pattern=6,
        iterations=24,
        config=guard_mod.GuardConfig(check_every=8),
    )
    chunks = _chunks(tmp_path / "t", "guardspan")
    assert len(chunks) == 3
    _assert_span_invariants(chunks, guard=True)
    # The audit of chunk i is timed into chunk i+1's block.
    assert all("audit" in c["spans"] for c in chunks[1:])
    assert all("snapshot" in c["spans"] for c in chunks[1:])


def test_batch_spans_on_every_bucket_event(tmp_path):
    from gol_tpu.batch import GolBatchRuntime

    rng = np.random.default_rng(0)
    worlds = [
        (rng.random((64, 64)) < 0.3).astype(np.uint8) for _ in range(2)
    ] + [(rng.random((128, 128)) < 0.3).astype(np.uint8)]
    brt = GolBatchRuntime(
        worlds=worlds,
        telemetry_dir=str(tmp_path / "t"),
        run_id="batchspan",
        checkpoint_every=8,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    brt.run(16)
    chunks = _chunks(tmp_path / "t", "batchspan")
    assert len(chunks) == 2 * len(brt.buckets)
    _assert_span_invariants(chunks)
    totals = {}
    for c in chunks:
        for phase, secs in c["spans"].items():
            totals[phase] = totals.get(phase, 0.0) + secs
    # The batch loop's boundary crop is its own span phase.
    assert "host_fetch" in totals and totals["host_fetch"] > 0


def test_cli3d_spans(tmp_path):
    from gol_tpu import cli3d

    rc = cli3d.main(
        [
            "2", "16", "8", "512", "0",
            "--telemetry", str(tmp_path / "t"),
            "--run-id", "span3d",
            "--checkpoint-every", "4",
            "--checkpoint-dir", str(tmp_path / "ck"),
        ]
    )
    assert rc == 0
    chunks = _chunks(tmp_path / "t", "span3d")
    assert len(chunks) == 2
    _assert_span_invariants(chunks)
    assert "checkpoint" in chunks[1]["spans"]
