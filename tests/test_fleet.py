"""The serving fleet (docs/SERVING.md "The fleet").

In-process contracts for `gol_tpu/serve/fleet.py` and the fencing fold
in `gol_tpu/serve/journal.py`; the process-level drills (real SIGKILL,
supervisor restarts) live in scripts/fleet_smoke.py and the chaos
matrix's fleet cells.  Here:

- the consistent-hash ring pins routes between membership events and
  spreads distinct buckets across replicas;
- `bucket_key` (the front tier's jax-free restatement) agrees with the
  scheduler's own `_group_for` grouping for every engine;
- **the red/green fencing pin**: a replica restarted after its open
  intent was migrated away folds the intent `handed_off` and does NOT
  re-run it — delete the handoff record and the same journal DOES
  re-admit (the single-writer assumption this PR removes);
- the fold arbitration table: fenced completes lose, pre-handoff
  completes win, hand-backs re-own, epoch-less records are fenced;
- `fleet_replay` + `FleetFront` restore a crashed front tier's epoch
  and route map, then bump;
- `HostMonitor` verdict hysteresis (miss streaks, restore beats, slow
  advisories);
- the fleet-aware client: one-hop 307 follow, and 404s that survive an
  epoch change are fatal while mid-handoff 404s are not;
- the trace-identity pin: fleet mode off leaves single-server journal
  bytes free of `owner_epoch` entirely.
"""

from __future__ import annotations

import http.server
import json
import threading

import jax
import pytest

from gol_tpu.serve import journal as journal_mod
from gol_tpu.serve.client import SimClient
from gol_tpu.serve.fleet import (
    FleetFront,
    FleetServer,
    HashRing,
    ReplicaHandle,
    bucket_key,
    fleet_replay,
)
from gol_tpu.resilience.health import HostMonitor

jax.config.update("jax_platforms", "cpu")


# -- routing ------------------------------------------------------------------


def test_hash_ring_pins_and_spreads():
    members = ["r0", "r1", "r2"]
    ring = HashRing(members)
    keys = [
        (64, 64, "bitpack"), (64, 64, "dense"),
        (128, 128, "bitpack"), (128, 128, "dense"),
        (192, 192, "bitpack"), (256, 256, "dense"),
    ]
    first = [ring.lookup(k) for k in keys]
    # Deterministic: a rebuilt ring over the same members agrees.
    again = HashRing(members)
    assert [again.lookup(k) for k in keys] == first
    # Distinct buckets actually spread (64 vnodes/member).
    assert len(set(first)) > 1
    # Losing one member only remaps the dead member's keys.
    survivor_ring = HashRing(["r0", "r2"])
    for k, owner in zip(keys, first):
        if owner != "r1":
            assert survivor_ring.lookup(k) == owner


def test_hash_ring_empty_raises():
    with pytest.raises(RuntimeError, match="no alive replicas"):
        HashRing([]).lookup((64, 64, "bitpack"))


@pytest.mark.parametrize("size", [32, 64, 96, 128, 130])
@pytest.mark.parametrize(
    "engine", ["auto", "dense", "bitpack", "pallas_bitpack"]
)
def test_bucket_key_matches_scheduler_grouping(tmp_path, size, engine):
    """The front tier routes by the SAME (H, W, engine) the scheduler
    would group the request into — without importing the device stack.
    (`bitpack` on an unpackable width is the one divergence: the
    replica rejects it with 400, so it never forms a group.)"""
    from gol_tpu.serve.scheduler import ServeScheduler, ValidationError

    key = bucket_key(size, engine, 64)
    sched = ServeScheduler(str(tmp_path / "s"), quantum=64, slots=2)
    try:
        try:
            sched.submit(
                {"id": "k0", "pattern": 4, "size": size,
                 "generations": 4, "engine": engine}
            )
        except ValidationError:
            assert engine == "bitpack" and size % 32 != 0
            return
        (sched_key,) = sched._groups.keys()
        assert sched_key == key
    finally:
        sched.close()


# -- the fencing fold (red/green) ---------------------------------------------


def _admit_record(rid, owner_epoch=None, size=32):
    fields = {
        "request": {
            "id": rid, "pattern": 4, "size": size, "generations": 4,
            "engine": "auto", "deadline_s": None, "stream_stats": False,
        },
        "ordinal": 0,
        "trace_id": f"tr-{rid}-test",
    }
    if owner_epoch is not None:
        fields["owner_epoch"] = owner_epoch
    return journal_mod.record("admit", rid, **fields)


def _write_journal(path, records):
    j = journal_mod.Journal(str(path))
    try:
        for rec in records:
            j.append(rec)
    finally:
        j.close()
    return str(path)


def test_restarted_replica_does_not_rerun_migrated_intent(tmp_path):
    """The red/green pin this PR exists for: the journal used to assume
    one writer, so a restart re-admitted every open intent — including
    one the front tier had already migrated to another replica (a
    double run).  With the fencing fold, the handoff record makes the
    restart DROP it; without the handoff (green leg) the same journal
    still re-admits as before."""
    from gol_tpu.serve.scheduler import ServeScheduler

    state = tmp_path / "replica"
    state.mkdir()
    admit = _admit_record("mig0", owner_epoch=1)
    handoff = journal_mod.record(
        "handoff", "mig0", epoch=2, src="r0", dst="r1", by="fleet"
    )
    _write_journal(state / "journal.jsonl", [admit, handoff])

    events = []
    sched = ServeScheduler(
        str(state), quantum=64, slots=2,
        registry=type("R", (), {"observe": lambda self, r: events.append(r)})(),
    )
    try:
        # RED: fenced — not requeued, not re-run, not poll-able.
        assert sched.get_result("mig0") is None
        assert sched.outstanding() == 0
        fenced = [
            r for r in events
            if r.get("event") == "serve" and r.get("action") == "fenced"
        ]
        assert len(fenced) == 1 and fenced[0]["request_id"] == "mig0"
        assert fenced[0]["fence_epoch"] == 2
    finally:
        sched.close()

    # GREEN: the identical journal minus the handoff re-admits.
    state2 = tmp_path / "replica2"
    state2.mkdir()
    _write_journal(state2 / "journal.jsonl", [_admit_record("mig0", 1)])
    sched2 = ServeScheduler(str(state2), quantum=64, slots=2)
    try:
        assert sched2.outstanding() == 1
        assert sched2.get_result("mig0") is not None
    finally:
        sched2.close()


def test_fold_rejects_complete_from_fenced_epoch(tmp_path):
    """A straggler complete written under the old ownership epoch after
    the handoff landed does not count — exactly-once holds at the fold
    level even though the bytes are physically in the file."""
    path = _write_journal(
        tmp_path / "j.jsonl",
        [
            _admit_record("a", owner_epoch=1),
            journal_mod.record("handoff", "a", epoch=2, by="fleet"),
            journal_mod.record("start", "a", owner_epoch=1),
            journal_mod.record("complete", "a", owner_epoch=1),
        ],
    )
    entries, torn = journal_mod.replay(path)
    assert torn == 0
    assert entries["a"]["status"] == "handed_off"
    assert entries["a"]["fence_epoch"] == 2


def test_fold_complete_before_handoff_wins(tmp_path):
    """The result is durable; the front tier never migrates a completed
    intent — so a complete already folded when the handoff arrives
    stays completed."""
    path = _write_journal(
        tmp_path / "j.jsonl",
        [
            _admit_record("a", owner_epoch=1),
            journal_mod.record("complete", "a", owner_epoch=1),
            journal_mod.record("handoff", "a", epoch=2, by="fleet"),
        ],
    )
    entries, _ = journal_mod.replay(path)
    assert entries["a"]["status"] == "completed"


def test_fold_handback_reowns_at_newer_epoch(tmp_path):
    """An admit at an epoch >= the fence re-owns the id (the ring
    routed it back here after a later membership event); records from
    epochs older than the hand-back stay fenced."""
    path = _write_journal(
        tmp_path / "j.jsonl",
        [
            _admit_record("a", owner_epoch=1),
            journal_mod.record("handoff", "a", epoch=2, by="fleet"),
            journal_mod.record("complete", "a", owner_epoch=1),  # fenced
            _admit_record("a", owner_epoch=3),  # hand-back
            journal_mod.record("complete", "a", owner_epoch=3),
        ],
    )
    entries, _ = journal_mod.replay(path)
    assert entries["a"]["status"] == "completed"
    assert entries["a"]["admit"]["owner_epoch"] == 3


def test_fold_fences_epochless_records(tmp_path):
    """Legacy records with no owner_epoch fold as epoch 0: after a
    handoff they are fenced too — 'I never heard of epochs' is not a
    way to win an ownership race."""
    path = _write_journal(
        tmp_path / "j.jsonl",
        [
            _admit_record("a"),  # no owner_epoch (single-server style)
            journal_mod.record("handoff", "a", epoch=2, by="fleet"),
            journal_mod.record("complete", "a"),
        ],
    )
    entries, _ = journal_mod.replay(path)
    assert entries["a"]["status"] == "handed_off"


def test_scheduler_fence_drops_open_skips_terminal_and_unknown(tmp_path):
    from gol_tpu.serve.scheduler import ServeScheduler

    sched = ServeScheduler(str(tmp_path / "s"), quantum=64, slots=2)
    try:
        sched.submit(
            {"id": "f0", "pattern": 4, "size": 32, "generations": 4,
             "owner_epoch": 1}
        )
        sched.submit(
            {"id": "f1", "pattern": 4, "size": 32, "generations": 4,
             "owner_epoch": 1}
        )
        assert sched.fence(["f0", "nope"], epoch=2) == 1
        assert sched.outstanding() == 1
        # The fenced id is forgotten — its new owner answers for it now.
        assert sched.get_result("f0") is None
        # The fence journaled a handoff: a restart fold agrees.
        entries, _ = journal_mod.replay(sched._journal.path)
        assert entries["f0"]["status"] == "handed_off"
        assert entries["f0"]["terminal"]["by"] == "fence"
        assert entries["f1"]["status"] == "admitted"
        # Re-fencing an already-fenced id is a no-op.
        assert sched.fence(["f0"], epoch=3) == 0
    finally:
        sched.close()


def test_single_server_journal_carries_no_owner_epoch(tmp_path):
    """The trace-identity pin's journal half: without a fleet in front,
    no record mentions owner_epoch at all — folds (and bytes) are
    identical to pre-fleet journals."""
    from gol_tpu.serve.scheduler import ServeScheduler

    sched = ServeScheduler(str(tmp_path / "s"), quantum=64, slots=2)
    try:
        sched.submit(
            {"id": "p0", "pattern": 4, "size": 32, "generations": 4}
        )
        with open(sched._journal.path) as f:
            assert "owner_epoch" not in f.read()
    finally:
        sched.close()


# -- the front tier's own journal ---------------------------------------------


def _handles(tmp_path, names):
    out = []
    for n in names:
        d = tmp_path / n
        d.mkdir(exist_ok=True)
        out.append(
            ReplicaHandle(
                name=n, base_url=f"http://127.0.0.1:1/{n}",
                state_dir=str(d),
            )
        )
    return out


def test_fleet_replay_restores_epoch_routes_and_handoffs(tmp_path):
    path = _write_journal(
        tmp_path / "fleet.journal.jsonl",
        [
            journal_mod.record(
                "epoch", "epoch-1", epoch=1, members=["r0", "r1"],
                reason="boot",
            ),
            journal_mod.record(
                "route", "x", bucket="64x64:bitpack", replica="r0",
                epoch=1,
            ),
            journal_mod.record(
                "route", "y", bucket="64x64:dense", replica="r1",
                epoch=1,
            ),
            journal_mod.record(
                "epoch", "epoch-2", epoch=2, members=["r1"],
                reason="replica_dead:r0",
            ),
            journal_mod.record(
                "handoff", "x", epoch=2, src="r0", dst="r1", by="fleet",
            ),
        ],
    )
    epoch, members, routes = fleet_replay(path)
    assert epoch == 2 and members == ["r1"]
    assert routes["x"]["replica"] == "r1"  # the handoff re-routed it
    assert routes["x"]["epoch"] == 2
    assert routes["y"] == {
        "replica": "r1", "bucket": "64x64:dense", "epoch": 1,
    }


def test_front_restart_restores_routes_and_bumps_epoch(tmp_path):
    """A front-tier crash+restart reconstructs its route map from its
    own journal fold and ALWAYS bumps the epoch — requests proxied
    before the crash are distinguishable from those proxied after."""
    replicas = _handles(tmp_path, ["r0", "r1"])
    front = FleetFront(replicas, str(tmp_path))
    try:
        assert front.epoch == 1  # boot bump on a fresh journal
        status, payload = front.submit(
            {"pattern": 4, "size": 64, "generations": 4}, direct=True
        )
        assert status == 307
        rid = payload["id"]
        owner = payload["replica"]
    finally:
        front.close()

    reborn = FleetFront(_handles(tmp_path, ["r0", "r1"]), str(tmp_path))
    try:
        assert reborn.epoch == 2  # restored 1, bumped on boot
        # Routes journal replica NAMES; direct payloads carry the URL.
        assert owner.endswith(reborn._routes[rid]["replica"])
        status, payload = reborn.submit(
            {"pattern": 4, "size": 64, "generations": 4}, direct=True
        )
        assert payload["owner_epoch"] == 2
    finally:
        reborn.close()


def test_direct_mode_routes_same_bucket_to_same_replica(tmp_path):
    front = FleetFront(_handles(tmp_path, ["r0", "r1", "r2"]), str(tmp_path))
    try:
        owners = set()
        for _ in range(3):
            status, payload = front.submit(
                {"pattern": 4, "size": 64, "generations": 4},
                direct=True,
            )
            assert status == 307
            owners.add(payload["replica"])
        assert len(owners) == 1  # one bucket, one pinned owner
        status, payload = front.result("not-a-request")
        assert status == 404 and payload["routing_epoch"] == front.epoch
    finally:
        front.close()


# -- host monitor -------------------------------------------------------------


def test_host_monitor_dead_after_miss_streak_and_flap_damping():
    mon = HostMonitor(["r0", "r1"], miss_threshold=3, restore_beats=2)
    assert mon.alive == ["r0", "r1"]
    assert mon.beat("r0", ok=False) == []
    assert mon.beat("r0", ok=False) == []
    (dead,) = mon.beat("r0", ok=False)
    assert dead.kind == "replica_dead" and dead.alive == 1
    assert mon.alive == ["r1"]
    # One OK beat is not a restore (flap damping)...
    assert mon.beat("r0", ok=True, latency_s=0.01) == []
    assert not mon.is_alive("r0")
    # ...and a miss resets the streak.
    assert mon.beat("r0", ok=False) == []
    assert mon.beat("r0", ok=True, latency_s=0.01) == []
    (restore,) = mon.beat("r0", ok=True, latency_s=0.01)
    assert restore.kind == "replica_restore" and restore.alive == 2
    assert mon.alive == ["r0", "r1"]


def test_host_monitor_slow_advisory_does_not_change_membership():
    mon = HostMonitor(
        ["r0"], latency_factor=8.0, min_samples=3, min_latency_s=0.001
    )
    for _ in range(4):
        assert mon.beat("r0", ok=True, latency_s=0.01) == []
    (slow,) = mon.beat("r0", ok=True, latency_s=0.2)
    assert slow.kind == "replica_slow"
    assert slow.latency_s == pytest.approx(0.2)
    assert slow.baseline_s == pytest.approx(0.01)
    assert mon.alive == ["r0"]  # advisory only
    # The slow probe is excluded from its own baseline window.
    assert mon.baseline("r0") == pytest.approx(0.01)


def test_host_monitor_validates():
    with pytest.raises(ValueError):
        HostMonitor([])
    with pytest.raises(ValueError):
        HostMonitor(["r0"], miss_threshold=0)


# -- the fleet-aware client ---------------------------------------------------


class _StubReplica(http.server.BaseHTTPRequestHandler):
    seen: list

    def log_message(self, *args):
        pass

    def _json(self, status, payload):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", "0"))
        body = json.loads(self.rfile.read(length))
        self.seen.append(body)
        self._json(202, {"id": body["id"], "status": "queued"})


def _stub_server(handler_cls):
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_client_follows_one_307_hop(tmp_path):
    """Direct mode end to end: the front answers a routing hint, the
    client re-POSTs to the replica itself, stamped with the id the
    front minted and the routing epoch it pinned."""
    seen = []
    stub = _stub_server(type("H", (_StubReplica,), {"seen": seen}))
    try:
        handle = ReplicaHandle(
            name="r0",
            base_url=f"http://127.0.0.1:{stub.server_address[1]}",
            state_dir=str(tmp_path / "r0"),
        )
        (tmp_path / "r0").mkdir()
        front = FleetFront([handle], str(tmp_path))
        server = FleetServer(front, 0, direct=True)
        try:
            client = SimClient(f"http://127.0.0.1:{server.port}")
            out = client.submit(
                {"pattern": 4, "size": 64, "generations": 4}
            )
            assert out["status"] == "queued"
            assert len(seen) == 1
            assert seen[0]["id"] == out["id"]
            assert seen[0]["owner_epoch"] == front.epoch
        finally:
            server.close()
            front.close()
    finally:
        stub.shutdown()
        stub.server_close()


class _Stub404(http.server.BaseHTTPRequestHandler):
    epochs: list  # routing_epoch per successive GET; None = no field

    def log_message(self, *args):
        pass

    def do_GET(self):
        epoch = self.epochs.pop(0) if self.epochs else self.epochs_last
        body = {"error": "unknown request"}
        if epoch is not None:
            body["routing_epoch"] = epoch
        payload = json.dumps(body).encode()
        self.send_response(404)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


def _client_against_404s(epochs, last):
    stub = _stub_server(
        type("H", (_Stub404,), {"epochs": list(epochs), "epochs_last": last})
    )
    return stub, SimClient(f"http://127.0.0.1:{stub.server_address[1]}")


def test_wait_for_plain_404_stays_immediately_fatal():
    stub, client = _client_against_404s([], None)
    try:
        with pytest.raises(KeyError, match="does not know"):
            client.wait_for("ghost", timeout_s=5.0, poll_s=0.01)
    finally:
        stub.shutdown()
        stub.server_close()


def test_wait_for_retries_404_through_one_epoch_then_fails():
    """A fleet 404 is a mid-handoff window, not a verdict: the poll
    holds while the epoch stands, and only a 404 observed under a LATER
    epoch — membership resolved, the fleet still has no route — is
    fatal."""
    stub, client = _client_against_404s([3, 3, 3], 4)
    try:
        with pytest.raises(KeyError, match="epoch 3 -> 4"):
            client.wait_for("mig", timeout_s=10.0, poll_s=0.01)
    finally:
        stub.shutdown()
        stub.server_close()


def test_wait_for_same_epoch_404_times_out_not_keyerror():
    stub, client = _client_against_404s([], 7)
    try:
        with pytest.raises(TimeoutError):
            client.wait_for("mig", timeout_s=0.3, poll_s=0.01)
    finally:
        stub.shutdown()
        stub.server_close()
