"""Cross-run perf ledger: ingestion adapters, the regression gate, and
the ``summarize --ledger`` anomaly (docs/OBSERVABILITY.md).

The committed ``PERF_LEDGER.jsonl`` is itself a fixture here: the gate
must pass on it at HEAD (the acceptance baseline) and must fail on a
copy with an injected >20% slow record — a gate that has never fired is
a gate that does not work.
"""

from __future__ import annotations

import json
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import ledger as ledger_mod
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

REPO = pathlib.Path(__file__).resolve().parent.parent
LEDGER = REPO / "PERF_LEDGER.jsonl"


# -- adapters over the committed artifacts -----------------------------------


def test_bench_adapter_recovers_truncated_r05_claims():
    recs = ledger_mod.normalize_artifact(str(REPO / "BENCH_r05.json"))
    fps = {r["fingerprint"] for r in recs}
    assert "bench:tpu:flagship_2d:16384^2x10240" in fps
    flag = next(
        r for r in recs
        if r["fingerprint"] == "bench:tpu:flagship_2d:16384^2x10240"
    )
    assert flag["value"] > 1.9e12 and flag["mfu"] == 0.663
    assert flag["backend"] == "tpu" and flag["round"] == 5


def test_bench_adapter_parses_intact_tails():
    recs = ledger_mod.normalize_artifact(str(REPO / "BENCH_r03.json"))
    assert len(recs) == 1
    assert recs[0]["mfu"] == 0.646
    assert recs[0]["kind"] == "throughput"


def test_batch_sparse_adapters_are_cpu_rows():
    batch = ledger_mod.normalize_artifact(str(REPO / "BATCH_r06.json"))
    sparse = ledger_mod.normalize_artifact(str(REPO / "SPARSE_r07.json"))
    assert all(r["backend"] == "cpu" for r in batch + sparse)
    assert any("B64" in r["fingerprint"] or "B16" in r["fingerprint"]
               for r in batch)
    assert all(
        r["extra"]["speedup_vs_dense"] is not None for r in sparse
    )


def test_halo_adapter_is_attribution_never_gated():
    recs = ledger_mod.normalize_artifact(str(REPO / "HALO_r05.json"))
    assert recs and all(r["kind"] == "attribution" for r in recs)
    assert all(r["direction"] == "lower" for r in recs)
    # Attribution records never enter the gate, even on their backend.
    assert ledger_mod.check_records(recs, backends=("all",)) == []


def test_halo_sweep_artifact_ingests_with_mfu():
    """HALO_r07.json: the PR 9 k-vs-MFU sweep — header-routed, one
    record per (mode, k) cell with depth/mode in extra and the MFU
    column carried; skipped cells (non-8-multiple Pallas depths) never
    become records."""
    recs = ledger_mod.normalize_artifact(str(REPO / "HALO_r07.json"))
    assert recs and all(r["kind"] == "attribution" for r in recs)
    modes = {r["extra"]["shard_mode"] for r in recs}
    assert modes == {"explicit", "overlap", "pipeline"}
    depths = {r["extra"]["halo_depth"] for r in recs}
    assert {1, 2, 4, 8, 16} <= depths
    assert any(r.get("mfu") is not None for r in recs)
    assert all("skipped" not in r["fingerprint"] for r in recs)
    # Idempotent on the committed ledger: everything already present.
    assert ledger_mod.check_records(recs, backends=("all",)) == []


def test_bare_module_emitter_outputs_ingest(tmp_path):
    """The satellite: a bare `python -m gol_tpu.utils.halobench` /
    scalebench capture (flat JSON + header stamp) ingests with zero
    sniffing."""
    halo = {
        "header": {"schema": ledger_mod.ARTIFACT_SCHEMA,
                   "tool": "halobench", "backend": "cpu", "argv": []},
        "exchange_s": 1e-5, "step_s": 3e-5, "stencil_s": 2e-5,
        "exposed_exchange_s": 1e-5, "size": 256, "steps": 8,
        "mesh": {"rows": 4}, "devices": 4, "engine": "bitpack",
    }
    p = tmp_path / "halo.json"
    p.write_text(json.dumps(halo))
    recs = ledger_mod.normalize_artifact(str(p))
    assert len(recs) == 1 and recs[0]["value"] == 3e-5
    assert "bitpack" in recs[0]["fingerprint"]
    scale = {
        "header": {"schema": ledger_mod.ARTIFACT_SCHEMA,
                   "tool": "scalebench", "backend": "cpu", "argv": []},
        "size_per_chip": 256, "steps": 8, "engine": "dense",
        "mesh_kind": "1d", "platform": "cpu", "processes": 1,
        "rows": [{"devices": 2, "per_chip": 1e8, "efficiency": 0.9}],
    }
    p2 = tmp_path / "scale.json"
    p2.write_text(json.dumps(scale))
    recs2 = ledger_mod.normalize_artifact(str(p2))
    assert len(recs2) == 1 and recs2[0]["value"] == 1e8


def test_scale_and_multichip_adapters():
    scale = ledger_mod.normalize_artifact(str(REPO / "SCALE_r05.json"))
    assert any(r["fingerprint"].startswith("scale:tpu:") for r in scale)
    multi = ledger_mod.normalize_artifact(str(REPO / "MULTICHIP_r05.json"))
    assert multi[0]["kind"] == "equivalence" and multi[0]["value"] == 1.0


def test_header_stamped_artifact_routes_by_tool(tmp_path):
    payload = {
        "header": {"schema": ledger_mod.ARTIFACT_SCHEMA,
                   "tool": "batchbench", "backend": "cpu", "argv": []},
        "backend": "cpu",
        "size": 64,
        "iters": 32,
        "rows": [
            {"B": 2, "engine": "bitpack",
             "aggregate_updates_per_sec": 1e9,
             "per_world_updates_per_sec": 5e8,
             "per_world_speedup_vs_sequential": 1.5},
        ],
    }
    path = tmp_path / "custom_r09.json"
    path.write_text(json.dumps(payload))
    recs = ledger_mod.normalize_artifact(str(path))
    assert len(recs) == 1 and recs[0]["tool"] == "batchbench"
    assert recs[0]["round"] == 9


def test_unknown_artifact_raises(tmp_path):
    path = tmp_path / "junk.json"
    path.write_text(json.dumps({"hello": 1}))
    try:
        ledger_mod.normalize_artifact(str(path))
    except telemetry.SchemaError:
        return
    raise AssertionError("unrecognized artifact did not raise")


# -- telemetry-directory ingestion -------------------------------------------


def _tiny_run(tmp_path, run_id="ledg", rate=5e7):
    with telemetry.EventLog(
        str(tmp_path), run_id=run_id, process_index=0
    ) as ev:
        ev.run_header(
            {"driver": "2d", "engine": "auto", "resolved_engine": "bitpack",
             "height": 64, "width": 64, "mesh": None}
        )
        ev.chunk_event(0, 8, 8, 0.001, int(rate / 1000), 0.001)
        ev.emit(
            "summary", duration_s=0.001, cell_updates=int(rate / 1000),
            updates_per_sec=rate, phases={"total": 0.001},
        )


def test_telemetry_dir_ingests_to_one_record_per_run(tmp_path):
    _tiny_run(tmp_path)
    recs = ledger_mod.normalize_telemetry_dir(str(tmp_path))
    assert len(recs) == 1
    rec = recs[0]
    assert rec["tool"] == "telemetry"
    assert rec["fingerprint"] == "telemetry:cpu:2d:bitpack:64x64:meshnone"
    assert rec["value"] == 5e7
    assert rec["mfu"] == 0.001


def test_ingest_is_idempotent(tmp_path):
    run_dir = tmp_path / "runs"
    run_dir.mkdir()
    _tiny_run(run_dir)
    ledger = tmp_path / "L.jsonl"
    added, skipped = ledger_mod.append_records(
        str(ledger), ledger_mod.normalize(str(run_dir))
    )
    assert (added, skipped) == (1, 0)
    added, skipped = ledger_mod.append_records(
        str(ledger), ledger_mod.normalize(str(run_dir))
    )
    assert (added, skipped) == (0, 1)


# -- the gate -----------------------------------------------------------------


def test_check_passes_on_committed_ledger(capsys):
    assert LEDGER.exists(), "PERF_LEDGER.jsonl must be committed at HEAD"
    rc = summ_mod.main(["ledger", "check", "--ledger", str(LEDGER)])
    assert rc == 0
    assert "no regression" in capsys.readouterr().out


def test_check_flags_injected_slow_record(tmp_path, capsys):
    records = ledger_mod.read_ledger(str(LEDGER))
    baseline = next(
        r for r in records
        if r["fingerprint"] == "bench:tpu:flagship_2d:16384^2x10240"
    )
    bad = dict(baseline)
    bad["value"] = baseline["value"] * 0.5  # a 50% collapse
    bad["source"] = "BENCH_r99.json"
    inj = tmp_path / "inj.jsonl"
    shutil.copy(LEDGER, inj)
    with open(inj, "a") as f:
        f.write(json.dumps(bad) + "\n")
    rc = summ_mod.main(["ledger", "check", "--ledger", str(inj)])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "flagship_2d" in out


def test_check_tolerates_historical_dips():
    # A dip BETWEEN best and newest is history, not a live regression.
    recs = [
        ledger_mod._record("f:tpu:x", v, "u", f"s{i}", "t", "tpu")
        for i, v in enumerate([100.0, 60.0, 95.0])
    ]
    assert ledger_mod.check_records(recs) == []
    # ...but a slow NEWEST record fails.
    recs.append(ledger_mod._record("f:tpu:x", 60.0, "u", "s3", "t", "tpu"))
    assert len(ledger_mod.check_records(recs)) == 1


def test_check_gates_tpu_only_by_default():
    recs = [
        ledger_mod._record("f:cpu:x", 100.0, "u", "s0", "t", "cpu"),
        ledger_mod._record("f:cpu:x", 10.0, "u", "s1", "t", "cpu"),
    ]
    assert ledger_mod.check_records(recs) == []
    assert len(ledger_mod.check_records(recs, backends=("all",))) == 1


def test_check_lower_is_better_direction():
    recs = [
        ledger_mod._record(
            "h:tpu:x", 1.0, "s", "s0", "t", "tpu",
            kind="throughput", direction="lower",
        ),
        ledger_mod._record(
            "h:tpu:x", 1.5, "s", "s1", "t", "tpu",
            kind="throughput", direction="lower",
        ),
    ]
    assert len(ledger_mod.check_records(recs)) == 1


def test_equivalence_flip_is_a_regression():
    recs = [
        ledger_mod._record(
            "m:tpu:8dev", 1.0, "ok", "s0", "t", "tpu", kind="equivalence"
        ),
        ledger_mod._record(
            "m:tpu:8dev", 0.0, "ok", "s1", "t", "tpu", kind="equivalence"
        ),
    ]
    assert len(ledger_mod.check_records(recs)) == 1


# -- summarize --ledger anomaly ----------------------------------------------


def test_summarize_flags_regression_against_ledger(tmp_path, capsys):
    run_dir = tmp_path / "runs"
    run_dir.mkdir()
    _tiny_run(run_dir, rate=5e7)
    ledger = tmp_path / "L.jsonl"
    best = ledger_mod._record(
        "telemetry:cpu:2d:bitpack:64x64:meshnone", 5e8, "cell-updates/s",
        "runs/old", "telemetry", "cpu",
    )
    ledger.write_text(json.dumps(best) + "\n")
    rc = summ_mod.main(
        ["summarize", str(run_dir), "--ledger", str(ledger)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "ANOMALY: regression" in out


def test_summarize_quiet_when_within_threshold(tmp_path, capsys):
    run_dir = tmp_path / "runs"
    run_dir.mkdir()
    _tiny_run(run_dir, rate=5e7)
    ledger = tmp_path / "L.jsonl"
    best = ledger_mod._record(
        "telemetry:cpu:2d:bitpack:64x64:meshnone", 5.5e7, "cell-updates/s",
        "runs/old", "telemetry", "cpu",
    )
    ledger.write_text(json.dumps(best) + "\n")
    rc = summ_mod.main(
        ["summarize", str(run_dir), "--ledger", str(ledger)]
    )
    assert rc == 0
    assert "regression" not in capsys.readouterr().out


def test_show_renders_trends(capsys):
    rc = summ_mod.main(["ledger", "show", "--ledger", str(LEDGER)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "best" in out and "flagship_2d" in out


def test_ledger_rejects_bad_lines(tmp_path):
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"ledger": 1}\n')
    try:
        ledger_mod.read_ledger(str(bad))
    except telemetry.SchemaError:
        return
    raise AssertionError("invalid ledger line did not raise")
