"""Schema v4 (batched multi-world fields) + v1/v2/v3 back-compat.

Companion to tests/test_telemetry.py (v1), test_telemetry_v2.py and
test_telemetry_v3.py.  Here:

- the v4 additions round-trip: the ``batch`` block on ``chunk`` and
  ``compile`` events (bucket shape, B, masked, engine, per-world
  throughput) and the batch run header;
- **back-compat**: ALL THREE committed fixtures — PR 2 (v1), PR 3 (v2)
  and PR 5 (v3) — still load, and a directory holding v1 + v2 + v3 + a
  freshly-written v4 stream merges and renders in one ``summarize``
  pass (exit 0), while a bogus schema still exits 2;
- the chunk-outlier anomaly classes batched records per bucket, so a
  big bucket sharing a take with a small one is not a false outlier.
"""

from __future__ import annotations

import io
import json
import pathlib
import shutil

import jax

from gol_tpu import telemetry
from gol_tpu.telemetry import summarize as summ_mod

jax.config.update("jax_platforms", "cpu")

DATA = pathlib.Path(__file__).parent / "data"
V1_FIXTURE = DATA / "telemetry_v1" / "pr2run.rank0.jsonl"
V2_FIXTURE = DATA / "telemetry_v2" / "pr3run.rank0.jsonl"
V3_FIXTURE = DATA / "telemetry_v3" / "pr5run.rank0.jsonl"

BATCH_BLOCK = {
    "bucket": [64, 64],
    "B": 8,
    "masked": True,
    "engine": "bitpack",
    "per_world_updates_per_sec": 1.2e7,
}


def _v4_stream(directory, run_id="v4"):
    with telemetry.EventLog(str(directory), run_id=run_id, process_index=0) as ev:
        ev.run_header(
            {
                "driver": "batch",
                "num_worlds": 8,
                "buckets": [
                    {"shape": [64, 64], "B": 8, "masked": True,
                     "engine": "bitpack", "sharded": False}
                ],
            }
        )
        ev.compile_event(4, 0.01, 0.09, batch=dict(BATCH_BLOCK))
        ev.chunk_event(0, 4, 4, 0.001, 131072, None, batch=dict(BATCH_BLOCK))
        return ev.path


def test_v4_batch_fields_roundtrip(tmp_path):
    path = _v4_stream(tmp_path)
    recs = [json.loads(ln) for ln in open(path)]
    # A fresh stream stamps the *current* schema (v5 at this round);
    # the v4 batch fields ride along unchanged — additive forever.
    assert recs[0]["schema"] == telemetry.SCHEMA_VERSION
    assert {1, 2, 3, 4} <= set(telemetry.SUPPORTED_SCHEMAS)
    compile_rec = recs[1]
    chunk_rec = recs[2]
    assert compile_rec["batch"]["bucket"] == [64, 64]
    assert chunk_rec["batch"]["B"] == 8
    assert chunk_rec["batch"]["per_world_updates_per_sec"] == 1.2e7


def test_committed_fixture_schemas_are_v1_v2_v3():
    for fixture, want in (
        (V1_FIXTURE, 1), (V2_FIXTURE, 2), (V3_FIXTURE, 3),
    ):
        head = json.loads(fixture.open().readline())
        assert head["schema"] == want, fixture


def test_v1_v2_v3_v4_merge_in_one_pass(tmp_path):
    for fixture in (V1_FIXTURE, V2_FIXTURE, V3_FIXTURE):
        shutil.copy(fixture, tmp_path / fixture.name)
    _v4_stream(tmp_path, run_id="now")
    out = io.StringIO()
    assert summ_mod.summarize(str(tmp_path), out) == 0
    text = out.getvalue()
    for run in ("pr2run", "pr3run", "pr5run", "now"):
        assert f"run {run}" in text
    assert "B=8" in text and "masked" in text


def test_unknown_schema_still_exits_2(tmp_path):
    rec = {
        "event": "run_header", "t": 1.0, "schema": 99, "run_id": "x",
        "process_index": 0, "process_count": 1, "config": {},
    }
    (tmp_path / "x.rank0.jsonl").write_text(json.dumps(rec) + "\n")
    assert summ_mod.main(["summarize", str(tmp_path)]) == 2


def test_chunk_outlier_classes_key_on_bucket(tmp_path):
    """Two buckets sharing a take must not flag each other as outliers."""
    with telemetry.EventLog(str(tmp_path), run_id="b", process_index=0) as ev:
        ev.run_header({"driver": "batch"})
        big = {"bucket": [256, 256], "B": 4, "masked": False,
               "engine": "bitpack"}
        small = {"bucket": [64, 64], "B": 4, "masked": False,
                 "engine": "bitpack"}
        for i in range(3):
            ev.chunk_event(i, 4, 4 * (i + 1), 0.010, 1 << 20, None,
                           batch=dict(big))
            ev.chunk_event(i, 4, 4 * (i + 1), 0.001, 1 << 16, None,
                           batch=dict(small))
    runs = summ_mod.load_dir(str(tmp_path))
    flags = summ_mod.find_anomalies(runs["b"])
    assert not [f for f in flags if "outlier" in f], flags
