"""Byte-exact dump format, round-trip reader, multi-rank file sets."""

import numpy as np
import pytest

from gol_tpu.utils import io as gol_io


def test_format_exact_bytes_small():
    """Pin the exact byte format of gol_printWorld (gol-main.c:17-28):
    'Row %2d: ' prefix (width-2, right-justified), '%u ' per cell with the
    trailing space, globalized row labels local_height*rank + i."""
    block = np.array([[0, 1, 0], [1, 1, 1], [0, 0, 1]], np.uint8)
    got = gol_io.format_world(block, rank=0)
    expected = b"Row  0: 0 1 0 \nRow  1: 1 1 1 \nRow  2: 0 0 1 \n"
    assert got == expected


def test_format_globalized_row_labels():
    block = np.zeros((3, 2), np.uint8)
    got = gol_io.format_world(block, rank=4)  # rows 12..14
    assert got.startswith(b"Row 12: 0 0 \n")
    assert b"Row 14: 0 0 \n" in got


def test_format_label_width_transition():
    """%2d pads single digits to width 2 and grows naturally past 99."""
    block = np.zeros((1, 1), np.uint8)
    assert gol_io.format_world(block, rank=5).startswith(b"Row  5: ")
    big = np.zeros((120, 1), np.uint8)
    text = gol_io.format_world(big, rank=0)
    assert b"Row  9: 0 \n" in text
    assert b"Row 10: 0 \n" in text
    assert b"Row 100: 0 \n" in text


def test_rank_file_banner():
    block = np.zeros((2, 2), np.uint8)
    data = gol_io.format_rank_file(block, rank=3)
    first = data.split(b"\n", 1)[0]
    assert first == (
        b"######################### FINAL WORLD IN RANK 3 IS "
        b"###############################"
    )


def test_write_and_read_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    board = rng.integers(0, 2, (12, 6)).astype(np.uint8)
    paths = gol_io.write_world_dumps(board, num_ranks=3, directory=str(tmp_path))
    assert [p.split("/")[-1] for p in paths] == [
        "Rank_0_of_3.txt",
        "Rank_1_of_3.txt",
        "Rank_2_of_3.txt",
    ]
    for r, path in enumerate(paths):
        row0, block = gol_io.read_rank_file(path)
        assert row0 == 4 * r
        np.testing.assert_array_equal(block, board[4 * r : 4 * (r + 1)])


def test_fast_and_generic_renderers_agree():
    rng = np.random.default_rng(1)
    block = rng.integers(0, 2, (5, 7)).astype(np.uint8)
    fast = gol_io.format_world(block, rank=2)
    lines = []
    for i, row in enumerate(block):
        lines.append(
            ("Row %2d: " % (5 * 2 + i)) + "".join("%u " % v for v in row) + "\n"
        )
    assert fast == "".join(lines).encode()


def test_indivisible_ranks_rejected():
    with pytest.raises(ValueError, match="divisible"):
        gol_io.write_world_dumps(np.zeros((10, 4), np.uint8), num_ranks=3)

def test_precreate_host_dump_files_single_process(tmp_path):
    """Writer-planned startup creation: single process owns every rank."""
    import os

    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import multihost

    mesh = mesh_mod.make_mesh_1d(4)
    paths = multihost.precreate_host_dump_files(
        mesh, (32, 8), 4, str(tmp_path)
    )
    assert [os.path.basename(p) for p in paths] == [
        f"Rank_{r}_of_4.txt" for r in range(4)
    ]
    assert all(os.path.getsize(p) == 0 for p in paths)
