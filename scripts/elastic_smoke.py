"""Elastic-smoke gate: live-mesh elasticity at the process level.

The check.sh stage for docs/RESILIENCE.md "Live elasticity".  The
in-process mechanics are covered by tests/test_redistribute.py,
tests/test_health.py, and the chaos matrix's elastic cells; this script
proves the end-to-end story through the real HTTP surface:

A ``--mesh-devices 4`` server (8 virtual CPU devices) runs a fault plan
that kills device 1 mid-serve, restores it six generations later, and
then inflates one chunk wall past the straggler watchdog.  A client
submits three mixed-size requests and polls them straight through the
whole drill.  Assertions:

- every request completes **byte-equal** to the sequential single-world
  oracle, with an uninterrupted 200/202 poll stream (``wait_for`` raises
  on any 404 — its success is the assertion);
- the server never restarts: device loss is absorbed by a live reshard
  (shrink), the restore regrows the mesh, and the v11 stream carries
  the ``device_loss``/``device_restore`` verdicts plus >= 2 ``live``
  reshard records — and NO restart marker;
- the straggler drill lands a ``straggler`` (and hedge) verdict without
  changing any result;
- ``/readyz`` answers 200 once the drill is over (readiness recovered),
  the journal is fully terminal, and the graceful ``/shutdown`` exits 0.

Exits non-zero with a message on any failure.
"""

from __future__ import annotations

import json
import os
import pathlib
import socket
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from gol_tpu.models import patterns  # noqa: E402
from gol_tpu.serve import journal as journal_mod  # noqa: E402
from gol_tpu.serve.client import SimClient  # noqa: E402
from gol_tpu.serve.scheduler import decode_board  # noqa: E402
from tests import oracle  # noqa: E402

GENS = 20
REQUESTS = [  # (id, pattern, size) — two share a bucket, one does not
    ("e0", 4, 32),
    ("e1", 6, 32),
    ("e2", 4, 64),
]

PLAN = {
    "faults": [
        # Kill device 1 at the generation-4 boundary; the health plane
        # reshards the live bucket groups onto the 2-device survivor
        # mesh, then regrows to 4 when the device comes back at 10.
        {"site": "device.loss", "at": 4, "device": 1, "restore_after": 6},
        # One chunk reports a 30s wall: the watchdog must flag it and
        # the guarded hedge replay must not change the result.
        {"site": "rank.slowdown", "at": 14, "delay_s": 30.0},
    ]
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _fail(msg: str) -> int:
    print(f"elastic-smoke: FAIL — {msg}")
    return 1


def _wait_healthy(client: SimClient, timeout_s: float = 120.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            client.healthz()
            return
        except Exception:
            time.sleep(0.25)
    raise TimeoutError("server never became healthy")


def _events(telemetry_dir: str):
    out = []
    d = pathlib.Path(telemetry_dir)
    if d.is_dir():
        for p in sorted(d.glob("*.jsonl*")):
            out.extend(json.loads(ln) for ln in open(p))
    return out


def run(tmp: str, env: dict) -> int:
    import numpy as np

    state = os.path.join(tmp, "state")
    tm = os.path.join(tmp, "tm")
    plan_path = os.path.join(tmp, "plan.json")
    pathlib.Path(plan_path).write_text(json.dumps(PLAN))
    port = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu.serve",
            "--state-dir", state, "--port", str(port),
            "--telemetry", tm, "--run-id", "elastic",
            "--chunk", "2", "--slots", "4", "--mesh-devices", "4",
        ],
        env={**env, "GOL_FAULT_PLAN": plan_path},
        cwd=str(REPO), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    client = SimClient(f"http://127.0.0.1:{port}", timeout=10.0)
    try:
        _wait_healthy(client)
        for rid, pat, size in REQUESTS:
            client.submit(
                {"id": rid, "pattern": pat, "size": size,
                 "generations": GENS}
            )
        # Poll through the loss, the reshard, the restore, and the
        # straggler: any 404 raises out of wait_for and fails the gate.
        results = {
            rid: client.wait_for(rid, timeout_s=180.0)
            for rid, _, _ in REQUESTS
        }
        status, payload = client._call("GET", "/readyz")
        if status != 200 or not payload.get("ready"):
            return _fail(
                f"/readyz {status} after the drill — readiness never "
                "recovered from the reshard window"
            )
        client.shutdown()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = proc.stdout.read()
    if rc != 0:
        return _fail(f"server exited {rc}:\n{out[-2000:]}")
    for rid, pat, size in REQUESTS:
        want = oracle.run_torus(patterns.init_global(pat, size, 1), GENS)
        if not np.array_equal(decode_board(results[rid]["board"]), want):
            return _fail(f"{rid}: result differs from sequential oracle")
    entries, _ = journal_mod.replay(os.path.join(state, "journal.jsonl"))
    if sorted(entries) != ["e0", "e1", "e2"] or not all(
        e["status"] == "completed" for e in entries.values()
    ):
        return _fail("journal not fully terminal after the drill")
    recs = _events(tm)
    from gol_tpu import telemetry

    headers = [r for r in recs if r.get("event") == "run_header"]
    if headers and headers[0].get("schema") != telemetry.SCHEMA_VERSION:
        return _fail(
            f"stream schema {headers[0].get('schema')} != "
            f"{telemetry.SCHEMA_VERSION}"
        )
    verdicts = [r["verdict"] for r in recs if r.get("event") == "health"]
    if "device_loss" not in verdicts:
        return _fail("no device_loss verdict — the loss never registered")
    if "device_restore" not in verdicts:
        return _fail("no device_restore verdict — the regrow never landed")
    if "straggler" not in verdicts:
        return _fail("no straggler verdict — the watchdog never fired")
    live = [r for r in recs if r.get("event") == "reshard" and r.get("live")]
    if len(live) < 2:
        return _fail(
            f"{len(live)} live reshard record(s) — expected the shrink "
            "AND the regrow"
        )
    if any(r.get("event") == "restart" for r in recs):
        return _fail(
            "a restart marker on the stream — device loss crashed the "
            "server instead of resharding it"
        )
    print(
        "elastic-smoke: OK — device loss shrank the mesh live, the "
        "restore regrew it, the straggler was hedged, and all "
        f"{len(REQUESTS)} requests completed byte-equal with an "
        "uninterrupted poll stream"
    )
    return 0


def main() -> int:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        # the live-elasticity drill needs a device ring to shrink
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    env.pop("GOL_FAULT_PLAN", None)
    env.pop("GOL_RESTART_ATTEMPT", None)
    with tempfile.TemporaryDirectory() as tmp:
        return run(tmp, env)


if __name__ == "__main__":
    sys.exit(main())
