"""Validate a Perfetto trace export against the committed schema.

check.sh's trace stage round-trips the committed v12 fixture through
``python -m gol_tpu.telemetry trace --perfetto`` and then runs this —
so the export format has CI teeth: a field rename or shape drift fails
the gate against ``docs/schemas/perfetto_trace.schema.json`` instead of
silently shipping a file Perfetto can no longer load.  Beyond the
schema, the structural invariants the schema language can't say are
checked here: complete (``ph: "X"``) events must carry non-negative
``ts``/``dur``, and every referenced ``tid`` must have a thread-name
metadata event.

Usage: python scripts/validate_trace_export.py EXPORT.json [SCHEMA.json]
"""

from __future__ import annotations

import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

DEFAULT_SCHEMA = REPO / "docs" / "schemas" / "perfetto_trace.schema.json"


def main(argv=None) -> int:
    from gol_tpu.telemetry.trace import validate_json_schema

    args = list(sys.argv[1:] if argv is None else argv)
    if not args or len(args) > 2:
        print(__doc__.strip().splitlines()[-1], file=sys.stderr)
        return 2
    export_path = args[0]
    schema_path = args[1] if len(args) == 2 else str(DEFAULT_SCHEMA)
    with open(export_path) as f:
        doc = json.load(f)
    with open(schema_path) as f:
        schema = json.load(f)

    errors = validate_json_schema(doc, schema)
    events = doc.get("traceEvents") or []
    named_tids = set()
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            continue
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            named_tids.add(ev.get("tid"))
        if ev.get("ph") == "X":
            for key in ("ts", "dur"):
                v = ev.get(key)
                if not isinstance(v, (int, float)) or v < 0:
                    errors.append(
                        f"$.traceEvents[{i}]: ph=X needs {key} >= 0, "
                        f"got {v!r}"
                    )
    missing = {
        ev.get("tid")
        for ev in events
        if isinstance(ev, dict) and ev.get("ph") == "X"
    } - named_tids
    if missing:
        errors.append(
            f"tids {sorted(missing)} have spans but no thread_name "
            "metadata event"
        )

    if errors:
        for e in errors:
            print(f"validate_trace_export: {e}", file=sys.stderr)
        return 1
    n_spans = sum(1 for ev in events if ev.get("ph") == "X")
    print(
        f"validate_trace_export: OK — {n_spans} span(s) on "
        f"{len(named_tids)} track(s) conform to "
        f"{pathlib.Path(schema_path).name}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
