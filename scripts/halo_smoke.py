"""Halo-pipeline smoke: the double-buffered chunk form, end to end.

check.sh stage (docs/DESIGN.md, PR 9).  A 512² glider run through the
real runtime dispatch with ``--shard-mode pipeline --halo-depth 4`` on a
1-D mesh must be (1) bit-identical to the explicit depth-1 run — the
pipeline may only move the exchange, never change the board — and
(2) stamped with schema-v8 ``halo`` blocks on every chunk event naming
the pipelined mode and depth it compiled.  A smoke that only checked
equality would pass with the knob silently ignored; the v8 block is the
receipt that the pipelined program actually ran.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# A virtual 4-device ring before the first backend touch.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.runtime import GolRuntime

    kw = dict(geometry=Geometry(size=512, num_ranks=1))
    mesh = mesh_mod.make_mesh_1d(4, devices=jax.devices()[:4])
    _, ref = GolRuntime(
        **kw, mesh=mesh, shard_mode="explicit", halo_depth=1
    ).run(pattern=5, iterations=48)

    with tempfile.TemporaryDirectory() as tdir:
        rt = GolRuntime(
            **kw,
            mesh=mesh,
            shard_mode="pipeline",
            halo_depth=4,
            telemetry_dir=tdir,
            run_id="halosmoke",
        )
        _, got = rt.run(pattern=5, iterations=48)

        if not np.array_equal(np.asarray(ref.board), np.asarray(got.board)):
            print(
                "FAIL: pipeline k=4 run diverges from explicit k=1 "
                "(the double buffer changed the board)"
            )
            return 1

        recs = [
            json.loads(ln)
            for ln in open(pathlib.Path(tdir) / "halosmoke.rank0.jsonl")
        ]
        chunks = [r for r in recs if r["event"] == "chunk"]
        if not chunks or any("halo" not in c for c in chunks):
            print("FAIL: chunk events missing the v8 halo block")
            return 1
        blocks = [c["halo"] for c in chunks]
        if any(
            b["mode"] != "pipeline" or b["depth"] != 4 for b in blocks
        ):
            print(f"FAIL: halo blocks do not pin pipeline/k=4: {blocks}")
            return 1
        exchanges = sum(b["exchanges"] for b in blocks)
        band_bytes = sum(b["band_bytes"] for b in blocks)

    print(
        f"halo smoke OK: 512² glider pipeline k=4 bit-equal to explicit "
        f"k=1 over 48 gens; v8 blocks on {len(chunks)} chunks "
        f"({exchanges} exchanges, {band_bytes} band bytes, "
        f"{100 * blocks[0]['exchange_share']:.2f}% traffic share)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
