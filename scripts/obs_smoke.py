"""Observability smoke: metrics endpoint + spans + ledger gate, end to end.

check.sh stage [8/9] (docs/OBSERVABILITY.md).  Drives the real CLI in a
subprocess with ``--metrics-port 0`` and asserts the continuous-
observability surface end to end:

1. the printed endpoint is scraped **while the run is alive** and
   returns parseable Prometheus text;
2. the scraped generation counter reconciles with the run's JSONL
   telemetry (it must equal a chunk-boundary generation the stream also
   recorded — one emission feeds both surfaces, so they cannot drift);
3. every chunk event carries a schema-v6 ``spans`` block whose
   dispatch+ready seconds match the chunk's fenced wall;
4. ``summarize`` renders the span phase-breakdown table and exits 0;
5. ``ledger check`` passes against the committed ``PERF_LEDGER.jsonl``
   (the CI regression gate over every artifact round at HEAD).
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

_METRIC_RE = re.compile(r"^gol_generation (\d+)", re.MULTILINE)


def scrape(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=2.0
    ) as resp:
        return resp.read().decode()


def main() -> int:
    env = {**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"}
    with tempfile.TemporaryDirectory() as tdir:
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "gol_tpu", "6", "64", "4096", "512",
                "0", "--telemetry", tdir, "--run-id", "obssmoke",
                "--checkpoint-every", "64", "--checkpoint-dir",
                os.path.join(tdir, "ck"), "--stats", "--metrics-port", "0",
            ],
            env=env,
            stdout=subprocess.PIPE,
            text=True,
            cwd=REPO,
        )
        try:
            # The CLI prints the bound ephemeral port before compiling.
            port = None
            deadline = time.monotonic() + 120.0
            assert proc.stdout is not None
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    break
                m = re.search(r"http://127\.0\.0\.1:(\d+)/metrics", line)
                if m:
                    port = int(m.group(1))
                    break
            if port is None:
                proc.kill()
                print("FAIL: CLI never printed the metrics endpoint")
                return 1

            # Scrape mid-run: retry until the run has stepped at least
            # one chunk (generation > 0) or finished.
            mid_text = None
            mid_gen = None
            while proc.poll() is None:
                try:
                    text = scrape(port)
                except OSError:
                    time.sleep(0.05)
                    continue
                m = _METRIC_RE.search(text)
                if m and int(m.group(1)) > 0:
                    mid_text, mid_gen = text, int(m.group(1))
                    break
                time.sleep(0.05)
            rest, _ = proc.communicate(timeout=300)
        finally:
            if proc.poll() is None:
                proc.kill()
        if proc.returncode != 0:
            print(f"FAIL: run exited {proc.returncode}")
            return 1
        if mid_text is None:
            print("FAIL: never scraped the live endpoint mid-run")
            return 1
        if "# TYPE gol_generation gauge" not in mid_text:
            print("FAIL: scrape is not Prometheus text exposition format")
            return 1

        recs = [
            json.loads(ln)
            for ln in open(pathlib.Path(tdir) / "obssmoke.rank0.jsonl")
        ]
        chunks = [r for r in recs if r["event"] == "chunk"]
        gens = {c["generation"] for c in chunks}
        if mid_gen not in gens:
            print(
                f"FAIL: scraped generation {mid_gen} is not a chunk "
                f"boundary the JSONL recorded ({sorted(gens)})"
            )
            return 1
        if any("spans" not in c for c in chunks):
            print("FAIL: chunk events missing the v6 spans block")
            return 1
        for c in chunks:
            inner = c["spans"]["dispatch"] + c["spans"]["ready"]
            if inner > c["wall_s"] * 1.05 + 1e-4:
                print(
                    f"FAIL: chunk {c['index']} spans dispatch+ready "
                    f"{inner:.6f}s exceed wall {c['wall_s']:.6f}s"
                )
                return 1

        summ = subprocess.run(
            [
                sys.executable, "-m", "gol_tpu.telemetry", "summarize",
                tdir,
            ],
            env=env,
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        if summ.returncode != 0 or "spans: phase" not in summ.stdout:
            print(
                f"FAIL: summarize rc={summ.returncode} or missing span "
                f"table\n{summ.stdout}\n{summ.stderr}"
            )
            return 1

    gate = subprocess.run(
        [
            sys.executable, "-m", "gol_tpu.telemetry", "ledger", "check",
            "--ledger", str(REPO / "PERF_LEDGER.jsonl"),
        ],
        env=env,
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    if gate.returncode != 0:
        print(
            f"FAIL: ledger check rc={gate.returncode}\n{gate.stdout}"
            f"{gate.stderr}"
        )
        return 1

    print(
        f"obs smoke OK: scraped generation {mid_gen} mid-run "
        f"(reconciles with {len(chunks)} chunk events), spans on every "
        f"chunk, summarize renders the phase table, ledger gate green"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
