"""Reshard smoke: cross-topology resume, end to end on CPU devices.

check.sh stage [9/10] (docs/RESILIENCE.md, "Elastic meshes").  A board
is evolved on a 2-D (4x2) block mesh, snapshotted in the sharded
piece-table format with the topology stamped into the manifest, then
resumed on a 1-D 8-ring — every destination row band assembled from two
source blocks — and run to the end.  The result must be (1) bit-equal
to a straight unmeshed run of the same total length — the reshard may
only move cells, never change them — and (2) an actual repartition:
the runtime must record a non-identity plan and stamp the schema-v7
``reshard`` telemetry event naming the 2d 4x2 -> 1d 8x1 move.  A smoke
that only checked equality would pass for a loader that ignores the
mesh; one that only checked the event would pass for a planner that
shuffles cells.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

SIZE = 256
MID = 24
REST = 40


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.resilience import reshard as rs
    from gol_tpu.runtime import GolRuntime
    from gol_tpu.utils import checkpoint as ckpt

    geom = Geometry(size=SIZE, num_ranks=1)

    # Straight oracle run: the whole evolution, unmeshed.
    _, ref = GolRuntime(geometry=geom, engine="bitpack").run(
        pattern=6, iterations=MID + REST
    )

    with tempfile.TemporaryDirectory() as tdir:
        # Evolve MID generations on the 2-D block mesh and snapshot it
        # in the stamped sharded format.
        mesh2d = mesh_mod.make_mesh_2d((4, 2))
        rt_src = GolRuntime(geometry=geom, engine="bitpack", mesh=mesh2d)
        _, mid_state = rt_src.run(pattern=6, iterations=MID)
        snap = ckpt.sharded_checkpoint_path(os.path.join(tdir, "ck"), MID)
        os.makedirs(os.path.dirname(snap), exist_ok=True)
        ckpt.save_sharded(
            snap,
            mid_state.board,
            MID,
            geom.num_ranks,
            mesh_layout=rs.MeshLayout.from_mesh(mesh2d).to_dict(),
        )
        if ckpt.verify_snapshot(snap) != MID:
            print("FAIL: freshly written sharded snapshot does not verify")
            return 1

        # Resume the 2-D snapshot on a 1-D ring — the cross-topology
        # load — and finish the run there.
        rt_dst = GolRuntime(
            geometry=geom,
            engine="bitpack",
            mesh=mesh_mod.make_mesh_1d(8),
            telemetry_dir=tdir,
            run_id="reshardsmoke",
        )
        _, final = rt_dst.run(pattern=6, iterations=REST, resume=snap)

        if not np.array_equal(np.asarray(final.board), np.asarray(ref.board)):
            print("FAIL: 2d-snapshot -> 1d-mesh resume diverges from the "
                  "straight run")
            return 1

        plan = rt_dst.last_reshard
        if plan is None:
            print("FAIL: cross-topology resume recorded no reshard plan")
            return 1
        if (
            plan["src_mesh"] != {"kind": "2d", "rows": 4, "cols": 2}
            or plan["dst_mesh"] != {"kind": "1d", "rows": 8, "cols": 1}
            or plan["moves"] <= plan["dst_shards"]
        ):
            print(f"FAIL: expected a true 2d 4x2 -> 1d 8x1 repartition, "
                  f"got {plan}")
            return 1

        recs = [
            json.loads(ln)
            for ln in open(pathlib.Path(tdir) / "reshardsmoke.rank0.jsonl")
        ]
        events = [r for r in recs if r["event"] == "reshard"]
        if len(events) != 1 or events[0]["bytes_moved"] != SIZE * SIZE // 8:
            print(f"FAIL: expected one v7 reshard event moving "
                  f"{SIZE * SIZE // 8} packed bytes, got {events}")
            return 1

    print(
        f"reshard smoke OK: 2d 4x2 snapshot resumed on 1d 8x1 bit-equal "
        f"to the straight run ({plan['moves']} moves, "
        f"{plan['bytes_moved']} packed bytes, "
        f"{plan['seam_splits']} seam splits)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
