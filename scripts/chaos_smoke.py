"""Chaos-smoke gate: one fault plan through a small guarded batch run.

The check.sh stage for the unified fault plane (docs/RESILIENCE.md).
ONE plan file arms three faults at once against a guarded, checkpointed,
telemetry-on ``--batch`` CLI run:

- an in-graph **bit-flip** at the final generation (the SDC the guard
  must catch and roll back),
- a **torn checkpoint write** (the ``.tmp`` must never become a resume
  candidate; the bounded retry must land a clean snapshot),
- a transient **ENOSPC** on a later snapshot (absorbed by the
  shed-telemetry-first policy's retry path).

Assertions: the CLI exits 0 with a guard line showing the detection,
every surviving snapshot fully verifies, the v9 ``fault``/``degraded``
records are on the stream, and each world's recovered final grid is
**byte-equal** to a clean (fault-free) run's.  Exits non-zero with a
message on any failure.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

WORLD = ["4", "64", "12", "512", "1"]
BATCH = ["--batch", "3", "--batch-sizes", "64,96"]

PLAN = {
    "faults": [
        {"site": "board.bitflip", "at": 12, "world": 1, "row": 10,
         "col": 20, "value": 165},
        {"site": "checkpoint.torn_tmp", "at": 4},
        {"site": "checkpoint.disk_full", "at": 8, "count": 1},
    ]
}


def _run(outdir: str, extra, env) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "gol_tpu", *WORLD, *BATCH,
         "--outdir", outdir, *extra],
        env=env, cwd=str(REPO), capture_output=True, text=True,
    )


def main() -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)}
    env.pop("XLA_FLAGS", None)
    with tempfile.TemporaryDirectory() as tmp:
        ref = os.path.join(tmp, "ref")
        out = os.path.join(tmp, "out")
        ck = os.path.join(tmp, "ck")
        tm = os.path.join(tmp, "tm")
        clean = _run(ref, [], env)
        if clean.returncode != 0:
            sys.exit(
                f"chaos smoke FAILED: clean run exited "
                f"{clean.returncode}:\n{clean.stdout}{clean.stderr}"
            )
        faulted = _run(
            out,
            ["--guard-every", "2", "--guard-redundant",
             "--checkpoint-every", "4", "--checkpoint-dir", ck,
             "--telemetry", tm, "--run-id", "chaossmoke",
             "--fault-plan", json.dumps(PLAN)],
            env,
        )
        if faulted.returncode != 0:
            sys.exit(
                f"chaos smoke FAILED: faulted run exited "
                f"{faulted.returncode}:\n{faulted.stdout}{faulted.stderr}"
            )
        # Detection: the guard line reports the failure + restore.
        guard_lines = [
            ln for ln in faulted.stdout.splitlines()
            if ln.startswith("GUARD")
        ]
        if not guard_lines or " 0 failures" in guard_lines[0]:
            sys.exit(
                "chaos smoke FAILED: the guard never detected the "
                f"injected flip (stdout:\n{faulted.stdout})"
            )
        print(f"chaos smoke: {guard_lines[0].strip()}")

        # Containment: every surviving snapshot verifies (the torn tmp
        # was retried to a clean file, never promoted).
        from gol_tpu.utils import checkpoint as ckpt

        snaps = ckpt.list_snapshots(ck, kind="batch")
        if not snaps:
            sys.exit("chaos smoke FAILED: no snapshots survived")
        for s in snaps:
            ckpt.verify_snapshot(s)
        print(
            f"chaos smoke: {len(snaps)} snapshot(s) verify after torn "
            "write + ENOSPC"
        )

        # The v9 records are on the stream.
        recs = []
        with open(os.path.join(tm, "chaossmoke.rank0.jsonl")) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        sites = sorted(
            {r["site"] for r in recs if r["event"] == "fault"}
        )
        for want in (
            "board.bitflip", "checkpoint.disk_full", "checkpoint.torn_tmp",
        ):
            if want not in sites:
                sys.exit(
                    f"chaos smoke FAILED: no v9 fault record for {want} "
                    f"(got {sites})"
                )
        if not any(r["event"] == "degraded" for r in recs):
            sys.exit(
                "chaos smoke FAILED: no v9 degraded record for the "
                "retried writes"
            )
        print(f"chaos smoke: v9 fault records for {', '.join(sites)}")

        # Recovery: every world's dump byte-equal to the clean run's.
        for w in range(3):
            name = os.path.join(f"world_{w:04d}", "Rank_0_of_1.txt")
            a = open(os.path.join(ref, name), "rb").read()
            b = open(os.path.join(out, name), "rb").read()
            if a != b:
                sys.exit(
                    f"chaos smoke FAILED: world {w} final grid differs "
                    "from the clean run"
                )
        print("chaos smoke: all 3 worlds byte-equal to the clean run")
    return 0


if __name__ == "__main__":
    sys.exit(main())
