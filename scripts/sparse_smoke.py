"""Sparse smoke: the activity tier's two contracts, end to end.

check.sh stage [7/8] (docs/SPARSE.md).  A Gosper-gun run in a 256²
arena through the real runtime dispatch must be (1) bit-identical to
the dense bitpack tier — the gate may only skip work, never change it —
and (2) actually *skip* a majority of tile-generations, with the
telemetry stream carrying the schema-v5 activity blocks that say so.
A smoke that only checked equality would pass for an engine that gates
nothing; one that only checked skipping would pass for an engine that
skips wrongly.
"""

from __future__ import annotations

import json
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    kw = dict(geometry=Geometry(size=256, num_ranks=1))
    _, ref = GolRuntime(**kw, engine="bitpack").run(pattern=7, iterations=64)

    with tempfile.TemporaryDirectory() as tdir:
        rt = GolRuntime(
            **kw,
            engine="activity",
            telemetry_dir=tdir,
            run_id="sparsesmoke",
        )
        _, got = rt.run(pattern=7, iterations=64)

        if not np.array_equal(np.asarray(ref.board), np.asarray(got.board)):
            print("FAIL: activity run diverges from the dense bitpack tier")
            return 1

        skipped = sum(a["skipped_tile_gens"] for a in rt.last_activity)
        tile_gens = sum(a["tile_gens"] for a in rt.last_activity)
        if skipped <= 0:
            print("FAIL: activity run skipped zero tile-generations")
            return 1

        recs = [
            json.loads(ln)
            for ln in open(
                pathlib.Path(tdir) / "sparsesmoke.rank0.jsonl"
            )
        ]
        chunks = [r for r in recs if r["event"] == "chunk"]
        if not chunks or any("activity" not in c for c in chunks):
            print("FAIL: chunk events missing the v5 activity block")
            return 1

    print(
        f"sparse smoke OK: gun bit-equal to bitpack, skipped "
        f"{skipped}/{tile_gens} tile-gens "
        f"({100 * skipped / tile_gens:.0f}%), tile "
        f"{rt.last_activity[0]['tile']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
