#!/usr/bin/env python
"""Resilience smoke drill (scripts/check.sh stage): preempt + auto-resume.

Runs the 2-D driver twice over the same world:

1. uninterrupted, recording the final rank dump's hash;
2. under ``python -m gol_tpu.resilience supervise`` with checkpointing +
   ``--auto-resume``, SIGTERM-ing the child once as soon as its first
   checkpoint lands — the child must exit 75 (preempted), the supervisor
   must relaunch it, and the resumed run must finish with a final dump
   **hashing identically** to the uninterrupted run.

Exit 0 on success; any assertion prints a diagnostic and exits 1.  Pure
stdlib + the repo (no pytest), CPU backend, a few seconds of wall clock.
The heavier kill-9 chaos matrix lives in tests/test_resilience_drill.py
(``-m slow``).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Big enough that ~27 chunks outlast the parent's signal latency by a
# wide margin, small enough to stay a smoke test.
WORLD = ["4", "1024", "54", "512", "1"]
CHUNK = "2"
DUMP = "Rank_0_of_1.txt"


def sha(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def fail(msg: str) -> None:
    print(f"resilience-drill: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    with tempfile.TemporaryDirectory() as tmp:
        ref = os.path.join(tmp, "ref")
        out = os.path.join(tmp, "out")
        ck = os.path.join(tmp, "ck")
        manifest = os.path.join(tmp, "job.manifest.json")
        os.makedirs(ref)
        os.makedirs(out)

        print("resilience-drill: [1/3] uninterrupted reference run")
        subprocess.run(
            [sys.executable, "-m", "gol_tpu", *WORLD, "--outdir", ref],
            env=env, cwd=REPO, check=True,
        )
        want = sha(os.path.join(ref, DUMP))

        print("resilience-drill: [2/3] supervised run, SIGTERM once")
        sup = subprocess.Popen(
            [
                sys.executable, "-m", "gol_tpu.resilience", "supervise",
                "--max-restarts", "3", "--backoff-base", "0",
                "--manifest", manifest, "--checkpoint-dir", ck, "--",
                sys.executable, "-m", "gol_tpu", *WORLD,
                "--outdir", out,
                "--checkpoint-every", CHUNK, "--checkpoint-dir", ck,
                "--auto-resume",
            ],
            env=env, cwd=REPO,
        )
        # Signal the CHILD (not the supervisor: signalling the supervisor
        # means "stop the job") once its first checkpoint is durable.
        deadline = time.time() + 120
        child_pid = None
        while time.time() < deadline:
            if sup.poll() is not None:
                fail(
                    f"supervisor exited {sup.returncode} before the drill "
                    "could signal the child"
                )
            has_ckpt = os.path.isdir(ck) and any(
                n.endswith(".gol.npz") for n in os.listdir(ck)
            )
            if has_ckpt and os.path.exists(manifest):
                with open(manifest) as f:
                    m = json.load(f)
                att = m.get("attempts") or []
                if att and att[-1].get("pid"):
                    child_pid = att[-1]["pid"]
                    break
            time.sleep(0.02)
        if child_pid is None:
            sup.kill()
            fail("no checkpoint/manifest appeared within 120s")
        try:
            os.kill(child_pid, signal.SIGTERM)
        except ProcessLookupError:
            pass  # child already finished this attempt — assert below
        rc = sup.wait(timeout=240)
        if rc != 0:
            fail(f"supervisor exited {rc}; see manifest {manifest}")

        print("resilience-drill: [3/3] verify manifest + final-grid hash")
        with open(manifest) as f:
            m = json.load(f)
        codes = [a["exit_code"] for a in m["attempts"]]
        if 75 not in codes[:-1]:
            fail(
                f"expected a preempted (75) attempt before the final one, "
                f"got exit codes {codes} — the SIGTERM raced the run; "
                "see the manifest"
            )
        if codes[-1] != 0 or not m.get("finished"):
            fail(f"final attempt did not finish cleanly: {codes}")
        got = sha(os.path.join(out, DUMP))
        if got != want:
            fail(
                f"final grid hash mismatch after preempt+resume: "
                f"{got} != {want}"
            )
        print(
            f"resilience-drill: OK — attempts {codes}, final grid "
            f"sha256 {got[:16]}... matches uninterrupted run"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
