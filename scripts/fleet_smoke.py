"""Fleet-smoke gate: the replicated front tier's process-level drill.

The check.sh stage for docs/SERVING.md "The fleet".  Everything
in-process is covered by tests/test_fleet.py and the chaos matrix's
fleet cells; this script exercises what needs REAL process death across
a REAL process boundary:

``python -m gol_tpu.serve.fleet`` runs a front tier over three
supervised replicas.  A client submits twelve mixed-bucket requests
through the front, then the drill ``kill -9``s the replica that owns
the most routed work, mid-flight.  Assertions:

- all twelve requests complete **exactly once** (fold-level: across the
  three replica journals, each id folds to ``completed`` on exactly one
  replica) and every board is **byte-equal** to the sequential
  single-world oracle — migration preserved results bit-for-bit;
- the front tier journaled and emitted at least one ``handoff`` (the
  dead replica's open intents moved to survivors under the same ids);
- the RESTARTED replica's journal fold shows the migrated intents
  ``handed_off`` and its event stream carries the ``fenced`` replay
  markers — it re-ran nothing (ownership fencing);
- ``GET /readyz`` flips to ``degraded: true`` while the replica is out
  and back to ``degraded: false`` once the supervisor's relaunch is
  re-admitted to the ring;
- a graceful ``POST /shutdown`` drains the whole fleet and the front
  process exits 0.

Exits non-zero with a message on any failure.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from gol_tpu.models import patterns  # noqa: E402
from gol_tpu.serve import journal as journal_mod  # noqa: E402
from gol_tpu.serve.client import Backpressure, SimClient  # noqa: E402
from gol_tpu.serve.fleet import HashRing, bucket_key  # noqa: E402
from tests import oracle  # noqa: E402

GENS = 400  # long enough that a kill lands mid-flight, even post-compile
REPLICAS = 3
#: (id, pattern, size, engine) — four buckets, three requests each:
#: 64/128 x auto(bitpack)/dense.  Mixed buckets prove the ring spreads
#: load AND that migration re-resolves each bucket independently.
REQUESTS = [
    (f"f{i:02d}", 4 + (i % 3), [64, 128][i % 2],
     ["auto", "dense"][(i // 2) % 2])
    for i in range(12)
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _fail(msg: str) -> int:
    print(f"fleet-smoke: FAIL — {msg}")
    return 1


def _oracle_board(pattern: int, size: int, gens: int):
    return oracle.run_torus(patterns.init_global(pattern, size, 1), gens)


def _events(telemetry_dir: str):
    out = []
    d = pathlib.Path(telemetry_dir)
    if d.is_dir():
        for p in sorted(d.glob("*.jsonl*")):  # incl. rotated attempt-0
            for ln in open(p):
                try:
                    out.append(json.loads(ln))
                except json.JSONDecodeError:
                    pass  # a SIGKILL may tear the victim's last line
    return out


def _victim() -> str:
    """The replica the ring will route the most requests to — computed
    with the SAME bucket_key/HashRing the front uses, so the drill
    always kills a replica that owns in-flight work."""
    ring = HashRing([f"r{k}" for k in range(REPLICAS)])
    load: dict = {}
    for _rid, _pat, size, engine in REQUESTS:
        owner = ring.lookup(bucket_key(size, engine, 64))
        load[owner] = load.get(owner, 0) + 1
    return max(sorted(load), key=lambda n: load[n])


def _manifest_pid(state: str, name: str) -> int:
    path = os.path.join(state, name, "manifest.json")
    return json.load(open(path))["attempts"][-1]["pid"]


def _submit_all(client: SimClient) -> None:
    for rid, pat, size, engine in REQUESTS:
        body = {
            "id": rid, "pattern": pat, "size": size,
            "generations": GENS, "engine": engine,
        }
        deadline = time.time() + 60
        while True:
            try:
                client.submit(body, connect_retries=3)
                break
            except Backpressure as e:
                if time.time() > deadline:
                    raise
                time.sleep(e.retry_after or 0.5)


def main() -> int:
    import numpy as np

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)}
    env.pop("XLA_FLAGS", None)
    env.pop("GOL_FAULT_PLAN", None)
    env.pop("GOL_RESTART_ATTEMPT", None)

    with tempfile.TemporaryDirectory(prefix="gol-fleet-smoke-") as tmp:
        state = os.path.join(tmp, "fleet")
        tm = os.path.join(tmp, "tm")
        port = _free_port()
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "gol_tpu.serve.fleet",
                "--state-dir", state, "--port", str(port),
                "--replicas", str(REPLICAS),
                "--telemetry", tm, "--run-id", "fleetsmoke",
                "--probe-interval", "0.1", "--chunk", "4",
                "--max-restarts", "3",
            ],
            env=env, cwd=str(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            front = SimClient(f"http://127.0.0.1:{port}", timeout=30.0)
            deadline = time.time() + 180  # 3 replicas cold-import jax
            while True:
                try:
                    front.healthz()
                    break
                except Exception:
                    if proc.poll() is not None:
                        out = proc.stdout.read() if proc.stdout else ""
                        return _fail(
                            f"fleet exited {proc.returncode} before "
                            f"healthy:\n{out[-2000:]}"
                        )
                    if time.time() > deadline:
                        return _fail("front tier never became healthy")
                    time.sleep(0.25)

            status, ready = front._call("GET", "/readyz")
            if status != 200 or ready.get("degraded"):
                return _fail(f"fleet not clean at start: {ready}")

            _submit_all(front)

            # kill -9 the owner of the heaviest bucket, mid-flight.
            victim = _victim()
            os.kill(_manifest_pid(state, victim), signal.SIGKILL)

            saw_degraded = False
            deadline = time.time() + 60
            while time.time() < deadline:
                status, ready = front._call("GET", "/readyz")
                if ready.get("degraded"):
                    saw_degraded = True
                    break
                time.sleep(0.05)
            if not saw_degraded:
                return _fail(
                    f"/readyz never reported degraded after killing "
                    f"{victim}"
                )

            results = {}
            for rid, _pat, _size, _engine in REQUESTS:
                results[rid] = front.wait_for(
                    rid, timeout_s=300.0, connect_retries=5
                )

            from gol_tpu.serve.scheduler import decode_board

            for i, (rid, pat, size, _engine) in enumerate(REQUESTS):
                want = _oracle_board(pat, size, GENS)
                got = decode_board(results[rid]["board"])
                if not np.array_equal(got, want):
                    return _fail(
                        f"{rid} board differs from the oracle after "
                        f"migration"
                    )

            # Exactly-once at fold level: each id folds to completed on
            # exactly one replica, across all three journals.
            folds = {}
            for k in range(REPLICAS):
                jpath = os.path.join(state, f"r{k}", "journal.jsonl")
                entries, _torn = journal_mod.replay(jpath)
                folds[f"r{k}"] = entries
            for rid, _pat, _size, _engine in REQUESTS:
                done_on = [
                    n for n, entries in folds.items()
                    if entries.get(rid, {}).get("status") == "completed"
                ]
                if len(done_on) != 1:
                    return _fail(
                        f"{rid} folds completed on {done_on!r} "
                        f"(want exactly one replica)"
                    )

            # The victim's fold shows its open intents handed off, and
            # its restart replayed them as fenced (no re-run).
            handed = [
                rid for rid, e in folds[victim].items()
                if e.get("status") == "handed_off"
            ]
            if not handed:
                return _fail(
                    f"no handed_off entries in {victim}'s journal fold"
                )
            victim_events = _events(os.path.join(state, victim, "telemetry"))
            fenced = [
                r for r in victim_events
                if r.get("event") == "serve" and r.get("action") == "fenced"
            ]
            if not fenced:
                return _fail(
                    f"restarted {victim} emitted no 'fenced' replay "
                    f"markers"
                )

            fleet_events = _events(tm)
            handoffs = [
                r for r in fleet_events
                if r.get("event") == "fleet" and r.get("action") == "handoff"
            ]
            if not handoffs:
                return _fail("front tier emitted no fleet handoff events")
            headers = [r for r in fleet_events if "schema" in r]
            from gol_tpu import telemetry

            if not headers or headers[0]["schema"] != telemetry.SCHEMA_VERSION:
                return _fail(
                    f"front stream header schema != "
                    f"{telemetry.SCHEMA_VERSION}"
                )

            # Recovery: the supervisor's relaunch rejoins the ring and
            # /readyz drops the degraded flag.
            recovered = False
            deadline = time.time() + 120
            while time.time() < deadline:
                status, ready = front._call("GET", "/readyz")
                if status == 200 and not ready.get("degraded"):
                    recovered = True
                    break
                time.sleep(0.2)
            if not recovered:
                return _fail("/readyz never recovered after the restart")

            status, fstat = front._call("GET", "/fleet/status")
            if fstat.get("handoffs_total", 0) < 1:
                return _fail(f"handoffs_total < 1 in {fstat}")

            front.shutdown()
            try:
                rc = proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                return _fail("fleet did not drain within 120s")
            out = proc.stdout.read() if proc.stdout else ""
            if rc != 0:
                return _fail(f"fleet exited {rc} after drain:\n{out[-2000:]}")
            if "fleet: drained" not in out:
                return _fail("fleet never printed its drain marker")
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

    print(
        f"fleet-smoke: OK — {len(REQUESTS)} requests exactly-once and "
        f"byte-equal across a replica kill ({len(handoffs)} handoffs, "
        f"victim {victim} fenced {len(handed)} intents)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
