"""Postmortem-smoke gate: black-box forensics on a REAL process death.

The check.sh stage for docs/OBSERVABILITY.md "Black box & postmortems".
Everything in-process is covered by tests/test_blackbox.py; this script
exercises the full crash-to-verdict story across process boundaries:

**Phase A — crash forensics.**  A real ``python -m gol_tpu.serve`` with
an armed ``crash.exit`` dies mid-batch (``os._exit``: no flushes, no
atexit — the black-box crash hook is the only forensic window).
Assertions: exactly one ``*.blackbox.jsonl`` dump exists, every line
schema-validates, and ``python -m gol_tpu.telemetry postmortem`` exits
0 with a verdict naming the request left open in the journal.

**Phase B — the verdict's promise.**  The same state dir relaunched
under ``python -m gol_tpu.resilience supervise``: the journal replay
re-admits the open request and completes it exactly once, byte-equal to
the sequential oracle — the postmortem's "a supervised replay will
re-admit and complete it" sentence, made true.

**Phase C — a clean death leaves no body.**  A SIGTERM drain exits 0
with NO dump anywhere (the graceful handler owns SIGTERM), and the
postmortem CLI says so with exit 1.

**Phase D — future dumps refuse.**  A dump stamped schema v(N+1) makes
the postmortem CLI exit 2 with the standard "newer than this reader
supports" message — never a KeyError three consumers deep.

Exits non-zero with a message on any failure.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from gol_tpu import telemetry  # noqa: E402
from gol_tpu.models import patterns  # noqa: E402
from gol_tpu.serve import journal as journal_mod  # noqa: E402
from gol_tpu.serve.client import SimClient  # noqa: E402
from gol_tpu.serve.scheduler import decode_board  # noqa: E402
from gol_tpu.telemetry import blackbox  # noqa: E402
from tests import oracle  # noqa: E402

GENS = 12
CRASH_CODE = 75
PLAN = {"faults": [{"site": "crash.exit", "at": 4, "value": CRASH_CODE}]}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _fail(msg: str) -> int:
    print(f"postmortem-smoke: FAIL — {msg}")
    return 1


def _wait_healthy(client: SimClient, timeout_s: float = 120.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            client.healthz()
            return
        except Exception:
            time.sleep(0.25)
    raise TimeoutError("server never became healthy")


def _serve_cmd(state: str) -> list:
    return [
        sys.executable, "-m", "gol_tpu.serve",
        "--state-dir", state, "--run-id", "pm", "--chunk", "4",
    ]


def _postmortem(env: dict, directory: str):
    """Run the CLI the way an operator would: (rc, stdout, stderr)."""
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu.telemetry", "postmortem",
         directory],
        env=env, cwd=str(REPO), capture_output=True, text=True,
        timeout=120,
    )
    return proc.returncode, proc.stdout, proc.stderr


def phase_a(tmp: str, env: dict) -> int:
    state = os.path.join(tmp, "state")
    port = _free_port()
    proc = subprocess.Popen(
        _serve_cmd(state) + ["--port", str(port)],
        env={**env, "GOL_FAULT_PLAN": json.dumps(PLAN)},
        cwd=str(REPO), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    client = SimClient(f"http://127.0.0.1:{port}", timeout=10.0)
    try:
        _wait_healthy(client)
        try:
            client.submit(
                {"id": "p0", "pattern": 4, "size": 64,
                 "generations": GENS},
                connect_retries=20, retry_delay_s=0.5,
            )
        except Exception:
            pass  # the crash can race the 202; the journal has the admit
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = proc.stdout.read()
    if rc != CRASH_CODE:
        return _fail(f"crash drill exited {rc}, not {CRASH_CODE}:"
                     f"\n{out[-2000:]}")

    dumps = blackbox.find_dumps(state)
    if len(dumps) != 1:
        return _fail(f"expected exactly one dump, found {dumps}")
    recs = blackbox.load_dump(dumps[0])  # raises on any invalid line
    head = recs[0]
    if head["config"]["driver"] != "blackbox":
        return _fail(f"dump header driver {head['config']['driver']}")
    if not head["config"]["reason"].startswith("crash.exit:gen"):
        return _fail(f"dump reason {head['config']['reason']}")
    if not any(
        r["event"] == "serve" and r["request_id"] == "p0" for r in recs
    ):
        return _fail("dump ring never saw request p0")

    entries, _ = journal_mod.replay(os.path.join(state, "journal.jsonl"))
    if entries.get("p0", {}).get("status") not in ("admitted", "started"):
        return _fail(f"journal fold {entries.get('p0')} — p0 not open")

    rc, stdout, stderr = _postmortem(env, state)
    if rc != 0:
        return _fail(f"postmortem CLI exited {rc}: {stderr[-500:]}")
    if "request(s) p0 left open in the journal" not in stdout:
        return _fail(f"verdict does not name p0:\n{stdout[-1000:]}")
    print(
        "postmortem-smoke: phase A ok — crash.exit mid-batch left a "
        "valid dump; the verdict names p0 as the request a replay "
        "recovers"
    )
    return 0


def phase_b(tmp: str, env: dict) -> int:
    import numpy as np

    state = os.path.join(tmp, "state")  # the SAME crashed state dir
    port = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu.resilience", "supervise",
            "--max-restarts", "3", "--backoff-base", "0.1",
            "--backoff-seed", "0", "--",
        ]
        + _serve_cmd(state) + ["--port", str(port)],
        env=env, cwd=str(REPO), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    client = SimClient(f"http://127.0.0.1:{port}", timeout=10.0)
    try:
        _wait_healthy(client)
        payload = client.wait_for(
            "p0", timeout_s=180.0, connect_retries=200
        )
        client.shutdown()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = proc.stdout.read()
    if rc != 0:
        return _fail(f"supervised replay exited {rc}:\n{out[-2000:]}")
    if payload["status"] != "done":
        return _fail(f"replayed p0 status {payload['status']}")
    want = oracle.run_torus(patterns.init_global(4, 64, 1), GENS)
    if not np.array_equal(decode_board(payload["board"]), want):
        return _fail("replayed p0 differs from the sequential oracle")
    raw = [
        json.loads(ln)
        for ln in open(os.path.join(state, "journal.jsonl"))
        if ln.strip()
    ]
    completes = [r["id"] for r in raw if r.get("rec") == "complete"]
    if completes != ["p0"]:
        return _fail(f"journal completes {completes} != exactly one p0")
    print(
        "postmortem-smoke: phase B ok — the supervised replay re-"
        "admitted p0 from the journal and completed it exactly once, "
        "byte-equal"
    )
    return 0


def phase_c(tmp: str, env: dict) -> int:
    state = os.path.join(tmp, "c_state")
    port = _free_port()
    proc = subprocess.Popen(
        _serve_cmd(state) + ["--port", str(port)],
        env=env, cwd=str(REPO), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    client = SimClient(f"http://127.0.0.1:{port}", timeout=10.0)
    try:
        _wait_healthy(client)
        client.submit(
            {"id": "c0", "pattern": 4, "size": 64, "generations": 40}
        )
        proc.send_signal(signal.SIGTERM)  # while c0 is in flight
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = proc.stdout.read()
    if rc != 0:
        return _fail(f"SIGTERM drain exited {rc}:\n{out[-2000:]}")
    stray = [
        str(p)
        for p in pathlib.Path(state).rglob(f"*{blackbox.DUMP_SUFFIX}")
    ]
    if stray:
        return _fail(f"graceful drain left dump(s): {stray}")
    rc, stdout, _ = _postmortem(env, state)
    if rc != 1 or "no *.blackbox.jsonl dump" not in stdout:
        return _fail(
            f"postmortem on a clean state: rc {rc}, not the designed "
            f"exit 1:\n{stdout[-500:]}"
        )
    print(
        "postmortem-smoke: phase C ok — SIGTERM drain exited 0 with no "
        "dump; postmortem reports the clean death with exit 1"
    )
    return 0


def phase_d(tmp: str, env: dict) -> int:
    d = os.path.join(tmp, "d_future")
    os.makedirs(d, exist_ok=True)
    future = telemetry.SCHEMA_VERSION + 1
    with open(os.path.join(d, f"fut{blackbox.DUMP_SUFFIX}"), "w") as f:
        f.write(json.dumps({
            "event": "run_header", "t": 0.0, "schema": future,
            "run_id": "fut", "process_index": 0, "process_count": 1,
            "config": {"driver": "blackbox", "reason": "smoke"},
        }) + "\n")
    rc, _, stderr = _postmortem(env, d)
    if rc != 2:
        return _fail(f"future-schema dump exited {rc}, not 2")
    if f"schema v{future} is newer than this reader supports" not in stderr:
        return _fail(f"future-schema message missing:\n{stderr[-500:]}")
    print(
        "postmortem-smoke: phase D ok — a v%d dump refuses with exit 2"
        % future
    )
    return 0


def main() -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)}
    for k in ("XLA_FLAGS", "GOL_FAULT_PLAN", "GOL_RESTART_ATTEMPT",
              "GOL_BLACKBOX", "GOL_BLACKBOX_RING"):
        env.pop(k, None)
    with tempfile.TemporaryDirectory() as tmp:
        for phase in (phase_a, phase_b, phase_c, phase_d):
            rc = phase(tmp, env)
            if rc != 0:
                return rc
    print(
        "postmortem-smoke: OK — crash dump + verdict, replay kept the "
        "promise, clean drain left no body, future schemas refuse"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
