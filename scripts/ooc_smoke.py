"""OOC smoke: the streaming tier's three contracts, end to end.

check.sh stage [19/19] (docs/STREAMING.md).  A Gosper-gun run pushed
through the real runtime dispatch (``--engine ooc``) must be:

1. **out-of-core for real** — the packed board is at least 4x the
   rotation's device footprint (the simulated budget the plan commits
   to), so the device never saw the whole board at once;
2. **bit-identical** to the in-core bitpack tier on the same pattern —
   streaming through bands, alternating sweeps, deferred drains and the
   wrap buffer may never change the program, only its residency;
3. **actually streaming-aware** — dead bands were skipped (the gun is
   band-local; transfer must scale with active bands, not board area),
   and the telemetry stream carries the schema-v15 ``ooc`` block with a
   measured ``overlap_fraction`` on every chunk.

A smoke that only checked equality would pass for a tier that streams
nothing; one that only checked the footprint would pass for a tier that
streams wrongly.
"""

from __future__ import annotations

import json
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gol_tpu.models.state import Geometry
    from gol_tpu.runtime import GolRuntime

    kw = dict(geometry=Geometry(size=64, num_ranks=16))  # 1024 x 64 board
    _, ref = GolRuntime(**kw, engine="bitpack").run(pattern=7, iterations=48)

    with tempfile.TemporaryDirectory() as tdir:
        rt = GolRuntime(
            **kw,
            engine="ooc",
            halo_depth=3,  # k: generations amortized per band round-trip
            ooc_band_rows=13,
            ooc_budget_mb=0,
            telemetry_dir=tdir,
            run_id="oocsmoke",
        )
        plan = rt._ooc_plan
        ratio = plan.board_bytes / plan.device_bytes()
        if ratio < 4.0:
            print(
                f"FAIL: board {plan.board_bytes}B is only {ratio:.1f}x the "
                f"device footprint {plan.device_bytes()}B — not out-of-core"
            )
            return 1

        _, got = rt.run(pattern=7, iterations=48)

        if not np.array_equal(np.asarray(ref.board), np.asarray(got.board)):
            print("FAIL: streamed run diverges from the in-core bitpack tier")
            return 1

        skipped = sum(o["skipped_bands"] for o in rt.last_ooc)
        if skipped <= 0:
            print("FAIL: gun run skipped zero dead bands")
            return 1

        recs = [
            json.loads(ln)
            for ln in open(pathlib.Path(tdir) / "oocsmoke.rank0.jsonl")
        ]
        chunks = [r for r in recs if r["event"] == "chunk"]
        if not chunks or any("ooc" not in c for c in chunks):
            print("FAIL: chunk events missing the v15 ooc block")
            return 1
        if any("overlap_fraction" not in c["ooc"] for c in chunks):
            print("FAIL: ooc blocks missing the measured overlap_fraction")
            return 1

    visits = sum(o["visits"] for o in rt.last_ooc)
    ovl = max(c["ooc"]["overlap_fraction"] for c in chunks)
    print(
        f"ooc smoke OK: {plan.num_bands}-band plan, board {ratio:.1f}x the "
        f"{plan.device_bytes()}B device footprint, gun bit-equal to "
        f"bitpack, {skipped} dead-band skips vs {visits} visits, "
        f"peak overlap {100 * ovl:.0f}%"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
