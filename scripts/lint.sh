#!/usr/bin/env bash
# Lint gate: ruff over the package + tests (config in ruff.toml).
#
# Degrades honestly when ruff is not installed (the hermetic TPU image
# does not ship it): falls back to a full-tree compile check so syntax
# errors are still caught, and says so.  CI images with ruff get the
# real lint.
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    exec ruff check gol_tpu tests benchmarks bench.py
fi

echo "lint: ruff not installed; falling back to compile-only check" >&2
python -m compileall -q gol_tpu tests benchmarks bench.py
echo "lint: compile check passed (install ruff for the full lint)"
