"""Batch-smoke gate: bit-equality vs sequential + compile-cache hits.

The check.sh stage for the batched multi-world engine
(docs/BATCHING.md).  Three assertions, all on the CPU backend:

1. **bit-equality** — a batched run of B mixed-size worlds (two buckets,
   one masked) is bit-identical per world to sequential single-world
   runs of the existing engine;
2. **cache population** — a CLI batch run with ``--compile-cache DIR``
   leaves compiled-program entries in DIR;
3. **cache hit** — a *second process* running the identical workload
   adds zero new entries (every program served from the persistent
   cache).

Exits non-zero with a message on any failure.
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def check_bit_equality() -> None:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gol_tpu.batch import GolBatchRuntime
    from gol_tpu.ops import stencil

    rng = np.random.default_rng(11)
    shapes = [(64, 64), (48, 32), (64, 64), (96, 96)]
    worlds = [(rng.random(s) < 0.35).astype(np.uint8) for s in shapes]
    refs = [np.asarray(stencil.run(jnp.asarray(w.copy()), 12)) for w in worlds]
    brt = GolBatchRuntime(worlds=[w.copy() for w in worlds], engine="auto")
    _, out = brt.run(12)
    for i, ref in enumerate(refs):
        if not np.array_equal(out[i], ref):
            sys.exit(
                f"batch smoke FAILED: world {i} {shapes[i]} diverges from "
                "its sequential single-world run"
            )
    print(
        f"batch smoke: {len(worlds)} worlds in "
        f"{len(brt.buckets)} buckets bit-equal to sequential runs"
    )


def check_compile_cache() -> None:
    from gol_tpu.batch import cache as cache_mod

    with tempfile.TemporaryDirectory() as tmp:
        cache_dir = os.path.join(tmp, "cc")
        cmd = [
            sys.executable, "-m", "gol_tpu", "6", "64", "8", "512", "0",
            "--batch", "4", "--batch-sizes", "64,96",
            "--compile-cache", cache_dir,
        ]
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)}
        for attempt in (1, 2):
            subprocess.run(
                cmd, env=env, cwd=tmp, check=True, capture_output=True
            )
            entries = cache_mod.cache_entries(cache_dir)
            if attempt == 1:
                if not entries:
                    sys.exit(
                        "batch smoke FAILED: --compile-cache left no "
                        f"entries in {cache_dir}"
                    )
                first = entries
            elif entries != first:
                new = sorted(set(entries) - set(first))
                sys.exit(
                    "batch smoke FAILED: second run missed the persistent "
                    f"compilation cache (new entries: {new})"
                )
        print(
            f"batch smoke: compile cache populated ({len(first)} entries), "
            "second process added none (all hits)"
        )


def main() -> int:
    check_bit_equality()
    check_compile_cache()
    return 0


if __name__ == "__main__":
    sys.exit(main())
