"""Serve-smoke gate: the serving tier's two process-level drills.

The check.sh stage for docs/SERVING.md.  Everything in-process is
covered by tests/test_serve.py and the chaos matrix's serve cells; this
script exercises what needs REAL process death:

**Phase A — supervised crash drill.**  A server under
``python -m gol_tpu.resilience supervise`` with an armed fault plan:
``crash.exit`` kills the process mid-batch (attempt 0 only), a
``board.bitflip`` poisons one request's world on the relaunch (the
guard must catch and replay it), and a transient journal ``io_error``
exercises the bounded retry under restart.  A client submits three
mixed-size requests, tolerating connection drops by resubmitting the
SAME ids (admission is idempotent).  Assertions: the supervisor exits 0
after a graceful ``/shutdown``, every accepted request completed
**exactly once** (one ``complete`` journal record each), every result
is **byte-equal** to the sequential single-world oracle, and the stream
carries the v10 ``requeue`` records plus the restart marker.

**Phase B — graceful drain.**  An unsupervised server receives two
in-flight requests and a SIGTERM: it must stop admitting, finish the
committed work, exit 0, and leave byte-equal results + a fully-terminal
journal on disk.

Exits non-zero with a message on any failure.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from gol_tpu.models import patterns  # noqa: E402
from gol_tpu.serve import journal as journal_mod  # noqa: E402
from gol_tpu.serve.client import SimClient  # noqa: E402
from gol_tpu.serve.scheduler import decode_board  # noqa: E402
from tests import oracle  # noqa: E402

GENS = 12
REQUESTS = [  # (id, pattern, size) — two share a bucket, one does not
    ("q0", 4, 64),
    ("q1", 4, 64),
    ("q2", 4, 96),
]

PLAN = {
    "faults": [
        # Kill the process at the first chunk boundary (first attempt
        # only — the default attempts=1 cannot re-kill the recovery).
        {"site": "crash.exit", "at": 4},
        # Poison the SECOND admitted request's world on the relaunch;
        # the guard must catch it and replay only that bucket.
        {"site": "board.bitflip", "at": 8, "world": 1, "row": 3,
         "col": 5, "value": 165, "attempts": 2},
        # Two transient EIO hits on a journal append under restart —
        # absorbed by the bounded write_with_retry budget.  (NOT
        # disk_full: ENOSPC sheds the telemetry stream by design, which
        # would race the guard-audit records this drill asserts on.)
        {"site": "checkpoint.io_error", "at": 6, "count": 2,
         "attempts": 2},
    ]
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _fail(msg: str) -> int:
    print(f"serve-smoke: FAIL — {msg}")
    return 1


def _wait_healthy(client: SimClient, timeout_s: float = 120.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            client.healthz()
            return
        except Exception:
            time.sleep(0.25)
    raise TimeoutError("server never became healthy")


def _oracle_board(pattern: int, size: int, gens: int):
    return oracle.run_torus(patterns.init_global(pattern, size, 1), gens)


def _events(telemetry_dir: str):
    out = []
    d = pathlib.Path(telemetry_dir)
    if d.is_dir():
        for p in sorted(d.glob("*.jsonl*")):  # incl. rotated attempt-0
            out.extend(json.loads(ln) for ln in open(p))
    return out


def phase_a(tmp: str, env: dict) -> int:
    import numpy as np

    state = os.path.join(tmp, "a_state")
    tm = os.path.join(tmp, "a_tm")
    plan_path = os.path.join(tmp, "plan.json")
    pathlib.Path(plan_path).write_text(json.dumps(PLAN))
    port = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu.resilience", "supervise",
            "--max-restarts", "3", "--backoff-base", "0.1",
            "--backoff-seed", "0", "--",
            sys.executable, "-m", "gol_tpu.serve",
            "--state-dir", state, "--port", str(port),
            "--telemetry", tm, "--run-id", "smoke", "--chunk", "4",
        ],
        env={**env, "GOL_FAULT_PLAN": plan_path},
        cwd=str(REPO), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    client = SimClient(f"http://127.0.0.1:{port}", timeout=10.0)
    try:
        _wait_healthy(client)
        for rid, pat, size in REQUESTS:
            # The armed crash can land mid-submission: resubmitting the
            # same id across connection drops is the designed recovery.
            client.submit(
                {"id": rid, "pattern": pat, "size": size,
                 "generations": GENS},
                connect_retries=40, retry_delay_s=0.5,
            )
        results = {
            rid: client.wait_for(
                rid, timeout_s=180.0, connect_retries=200
            )
            for rid, _, _ in REQUESTS
        }
        client.shutdown()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = proc.stdout.read()
    if rc != 0:
        return _fail(f"supervised server exited {rc}:\n{out[-2000:]}")
    for rid, pat, size in REQUESTS:
        want = _oracle_board(pat, size, GENS)
        got = decode_board(results[rid]["board"])
        if not np.array_equal(got, want):
            return _fail(f"{rid}: result differs from sequential oracle")
    # Exactly once, straight from the durability artifact: every id has
    # completed status; no id completed twice (count raw records).
    raw = [
        json.loads(ln)
        for ln in open(os.path.join(state, "journal.jsonl"))
        if ln.strip()
    ]
    completes = [r["id"] for r in raw if r.get("rec") == "complete"]
    if sorted(completes) != ["q0", "q1", "q2"]:
        return _fail(f"journal completes {completes} != one per request")
    entries, _ = journal_mod.replay(os.path.join(state, "journal.jsonl"))
    if not all(e["status"] == "completed" for e in entries.values()):
        return _fail("journal left a non-terminal request behind")
    recs = _events(tm)
    if not any(
        r.get("event") == "serve" and r.get("action") == "requeue"
        for r in recs
    ):
        return _fail("no v10 requeue record — the restart never replayed")
    if not any(r.get("event") == "restart" for r in recs):
        return _fail("no restart marker on the stream")
    if not any(
        r.get("event") == "guard_audit" and not r.get("ok")
        for r in recs
    ):
        return _fail("the injected bitflip never failed an audit")
    from gol_tpu import telemetry

    headers = [r for r in recs if r.get("event") == "run_header"]
    if headers and headers[0].get("schema") != telemetry.SCHEMA_VERSION:
        return _fail(
            f"stream schema {headers[0].get('schema')} != "
            f"{telemetry.SCHEMA_VERSION}"
        )
    print(
        "serve-smoke: phase A ok — crash mid-batch, supervised restart "
        "re-admitted from the journal, every request completed exactly "
        "once, byte-equal"
    )
    return 0


def phase_b(tmp: str, env: dict) -> int:
    import numpy as np

    state = os.path.join(tmp, "b_state")
    tm = os.path.join(tmp, "b_tm")
    port = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu.serve",
            "--state-dir", state, "--port", str(port),
            "--telemetry", tm, "--run-id", "drain", "--chunk", "4",
        ],
        env=env, cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    client = SimClient(f"http://127.0.0.1:{port}", timeout=10.0)
    try:
        _wait_healthy(client)
        for rid in ("d0", "d1"):
            client.submit(
                {"id": rid, "pattern": 4, "size": 64,
                 "generations": 40}
            )
        proc.send_signal(signal.SIGTERM)  # while both are in flight
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = proc.stdout.read()
    if rc != 0:
        return _fail(f"SIGTERM drain exited {rc}:\n{out[-2000:]}")
    want = _oracle_board(4, 64, 40)
    for rid in ("d0", "d1"):
        path = os.path.join(state, "results", f"{rid}.json")
        if not os.path.exists(path):
            return _fail(f"{rid}: no result on disk after drain")
        payload = json.load(open(path))
        if payload["status"] != "done":
            return _fail(f"{rid}: drained result status {payload['status']}")
        if not np.array_equal(decode_board(payload["board"]), want):
            return _fail(f"{rid}: drained result differs from oracle")
    entries, _ = journal_mod.replay(os.path.join(state, "journal.jsonl"))
    if sorted(entries) != ["d0", "d1"] or not all(
        e["status"] == "completed" for e in entries.values()
    ):
        return _fail("journal not fully terminal after graceful drain")
    print(
        "serve-smoke: phase B ok — SIGTERM drained both in-flight "
        "requests to byte-equal results and exited 0"
    )
    return 0


def main() -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)}
    env.pop("XLA_FLAGS", None)
    env.pop("GOL_FAULT_PLAN", None)
    env.pop("GOL_RESTART_ATTEMPT", None)
    with tempfile.TemporaryDirectory() as tmp:
        rc = phase_a(tmp, env)
        if rc:
            return rc
        rc = phase_b(tmp, env)
        if rc:
            return rc
    print("serve-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
