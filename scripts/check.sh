#!/usr/bin/env bash
# The verify gate: everything a builder or reviewer must see green
# before trusting a change, in dependency order —
#
#   1. lint            (scripts/lint.sh: ruff, or compile-only fallback)
#   2. static verifier (python -m gol_tpu.analysis: engine invariants
#                       proven from traced programs, CPU-only)
#   3. telemetry smoke (tiny run with --telemetry; summarize must
#                       schema-validate the stream and exit 0)
#   4. stats smoke     (same run with --stats; summarize must exit 0
#                       and report a population row)
#   5. resilience drill (supervised run, SIGTERM the child once;
#                       auto-resume must finish with the same
#                       final-grid hash as an uninterrupted run)
#   6. batch smoke     (batched multi-world run bit-equal to
#                       sequential; --compile-cache populated on run 1,
#                       zero new entries on run 2 — all hits)
#   7. sparse smoke    (activity-gated glider-gun run bit-equal to the
#                       dense bitpack tier AND skipping >0 tiles, with
#                       v5 activity telemetry present)
#   8. obs smoke       (run with --metrics-port + v6 spans: scrape the
#                       live Prometheus endpoint mid-run, reconcile it
#                       with the JSONL, summarize the span table, and
#                       run `ledger check` against the committed
#                       PERF_LEDGER.jsonl regression gate)
#   9. reshard smoke   (elastic meshes: a 2-D-block sharded snapshot
#                       resumed on a 1-D ring must be bit-equal to a
#                       straight run, with a non-identity plan and the
#                       schema-v7 reshard event stamped)
#  10. halo smoke      (pipelined depth-k halo exchange: 512² glider,
#                       pipeline k=4 on a 1-D mesh bit-equal to
#                       explicit k=1, with v8 halo blocks on every
#                       chunk event)
#  11. chaos smoke     (unified fault plane: one plan driving
#                       bit-flip + torn-write + ENOSPC through a small
#                       guarded batch run — detected, contained, and
#                       recovered byte-equal; docs/RESILIENCE.md)
#  12. serve smoke     (serving tier, docs/SERVING.md: supervised
#                       crash mid-batch + journal re-admit — every
#                       accepted request completes exactly once,
#                       byte-equal — then a SIGTERM graceful drain)
#  13. elastic smoke   (live elasticity, docs/RESILIENCE.md: a sharded
#                       server loses a device mid-serve, live-reshards
#                       at the chunk boundary, regrows on restore,
#                       hedges a straggler — every request byte-equal,
#                       no restart, v11 verdicts on the stream)
#  14. lockcheck       (host-plane concurrency: lock-order graph,
#                       guarded-field discipline, SPMD collective
#                       consistency — AST-only, no jax backend;
#                       docs/ANALYSIS.md "The concurrency matrix")
#  15. trace smoke     (request tracing, docs/OBSERVABILITY.md: the
#                       committed v12 fixture round-trips through
#                       `telemetry trace --perfetto` and the export
#                       validates against the committed JSON schema —
#                       CI teeth for the export format)
#  16. tier-1 tests    (the exact ROADMAP.md command)
#  17. postmortem smoke (black box, docs/OBSERVABILITY.md: crash a
#                       real server via the fault plane, validate the
#                       *.blackbox.jsonl dump, run `telemetry
#                       postmortem` and assert the verdict names the
#                       open request; a supervised replay then keeps
#                       the verdict's promise; a graceful drain leaves
#                       no dump; a
#                       future-schema dump refuses with exit 2)
#  18. fleet smoke     (serving fleet, docs/SERVING.md "The fleet":
#                       3 supervised replicas behind the front tier,
#                       kill -9 one mid-flight — journaled handoff,
#                       ownership fencing on the restart, exactly-once
#                       byte-equal completion, degraded→recovered
#                       /readyz, graceful drain exit 0)
#  19. ooc smoke       (out-of-core tier, docs/STREAMING.md: a Gosper
#                       gun streamed through a device footprint the
#                       board is >=4x of — bit-equal to the in-core
#                       bitpack tier, dead bands skipped, v15 ooc
#                       blocks with measured overlap_fraction on
#                       every chunk)
#
# Any stage failing fails the gate.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== [1/19] lint =="
bash scripts/lint.sh

echo "== [2/19] static verifier (gol_tpu.analysis) =="
JAX_PLATFORMS=cpu python -m gol_tpu.analysis

echo "== [3/19] telemetry smoke (docs/OBSERVABILITY.md) =="
tdir="$(mktemp -d)"
trap 'rm -rf "$tdir"' EXIT
JAX_PLATFORMS=cpu python -m gol_tpu 0 64 8 512 0 \
    --telemetry "$tdir" --run-id smoke > /dev/null
JAX_PLATFORMS=cpu python -m gol_tpu.telemetry summarize "$tdir"

echo "== [4/19] stats smoke (in-graph simulation statistics) =="
sdir="$(mktemp -d)"
trap 'rm -rf "$tdir" "$sdir"' EXIT
JAX_PLATFORMS=cpu python -m gol_tpu 6 64 8 512 0 \
    --telemetry "$sdir" --run-id statsmoke --stats > /dev/null
JAX_PLATFORMS=cpu python -m gol_tpu.telemetry summarize "$sdir" \
    | tee /tmp/_stats_smoke.log
grep -q "stats     gen" /tmp/_stats_smoke.log

echo "== [5/19] resilience drill (docs/RESILIENCE.md) =="
JAX_PLATFORMS=cpu python scripts/resilience_drill.py

echo "== [6/19] batch smoke (docs/BATCHING.md) =="
JAX_PLATFORMS=cpu python scripts/batch_smoke.py

echo "== [7/19] sparse smoke (docs/SPARSE.md) =="
JAX_PLATFORMS=cpu python scripts/sparse_smoke.py

echo "== [8/19] obs smoke (docs/OBSERVABILITY.md) =="
JAX_PLATFORMS=cpu python scripts/obs_smoke.py

echo "== [9/19] reshard smoke (docs/RESILIENCE.md, elastic meshes) =="
JAX_PLATFORMS=cpu python scripts/reshard_smoke.py

echo "== [10/19] halo smoke (pipelined depth-k exchange, PR 9) =="
JAX_PLATFORMS=cpu python scripts/halo_smoke.py

echo "== [11/19] chaos smoke (docs/RESILIENCE.md, fault plane) =="
JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

echo "== [12/19] serve smoke (docs/SERVING.md, serving tier) =="
JAX_PLATFORMS=cpu python scripts/serve_smoke.py

echo "== [13/19] elastic smoke (docs/RESILIENCE.md, live elasticity) =="
python scripts/elastic_smoke.py

echo "== [14/19] lockcheck (host-plane concurrency, docs/ANALYSIS.md) =="
python -m gol_tpu.analysis --concurrency

echo "== [15/19] trace smoke (docs/OBSERVABILITY.md, request tracing) =="
JAX_PLATFORMS=cpu python -m gol_tpu.telemetry trace \
    tests/data/telemetry_v12 --perfetto /tmp/_trace_export.json
python scripts/validate_trace_export.py /tmp/_trace_export.json \
    docs/schemas/perfetto_trace.schema.json

echo "== [16/19] tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"

echo "== [17/19] postmortem-smoke (docs/OBSERVABILITY.md, black box) =="
make postmortem-smoke

echo "== [18/19] fleet smoke (docs/SERVING.md, the fleet) =="
JAX_PLATFORMS=cpu python scripts/fleet_smoke.py

echo "== [19/19] ooc smoke (docs/STREAMING.md, out-of-core tier) =="
JAX_PLATFORMS=cpu python scripts/ooc_smoke.py

exit "$rc"
